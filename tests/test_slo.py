"""Serving SLO burn-rate evaluation (ISSUE 12 tentpole, verdict side).

Everything here is closed-form: hand-built request records with known
retire ticks, so the fast/slow-window burn rates are exact fractions
and the multi-window breach logic is checkable case by case.  The
span-stream plumbing (records_from_spans over a recorder-fed
scheduler run) and the CLI/endpoint exit codes ride the same
deterministic streams.
"""

import json

import pytest

from distributed_tensorflow_example_tpu.obs import cli as cli_lib
from distributed_tensorflow_example_tpu.obs import schema as schema_lib
from distributed_tensorflow_example_tpu.obs import serve as serve_lib
from distributed_tensorflow_example_tpu.obs import slo as slo_lib
from distributed_tensorflow_example_tpu.obs import spans as spans_lib


def _records(n=100, bad_ticks=(), ttft_bad=900.0, ttft_good=100.0,
             error_ticks=()):
    """n requests retiring at ticks 1..n; bad_ticks get a slow ttft,
    error_ticks an engine error."""
    out = []
    for t in range(1, n + 1):
        out.append({
            "rid": t, "retire_tick": t,
            "ttft_ms": ttft_bad if t in bad_ticks else ttft_good,
            "latency_ms": 50.0,
            "error": t in error_ticks,
        })
    return out


def _spec(**kw):
    base = dict(name="ttft_p99_ms", metric="ttft_ms",
                threshold_ms=500.0, objective=0.99, fast_window=10,
                slow_window=100, burn_threshold=2.0)
    base.update(kw)
    return slo_lib.SLOSpec(**base)


# --- closed-form burn rates ------------------------------------------------


def test_burn_rates_exact_and_multi_window_breach():
    """2 bad requests inside the fast window: fast burn = (2/10)/0.01
    = 20, slow burn = (2/100)/0.01 = 2 — both >= 2.0 -> breach, with
    the exact numbers pinned."""
    doc = slo_lib.evaluate(_records(bad_ticks=(95, 100)),
                           specs=[_spec()], now_tick=100)
    s = doc["slos"][0]
    assert s["windows"]["fast"] == {
        "window_ticks": 10, "requests": 10, "bad": 2,
        "bad_frac": 0.2, "burn_rate": 20.0}
    assert s["windows"]["slow"] == {
        "window_ticks": 100, "requests": 100, "bad": 2,
        "bad_frac": 0.02, "burn_rate": 2.0}
    assert s["breach"] is True
    assert doc["breaches"] == ["ttft_p99_ms"]
    assert doc["ok"] is False
    assert doc["now_tick"] == 100 and doc["requests"] == 100


def test_old_badness_does_not_page():
    """The same 2 bad requests, but old (ticks 1, 2): the slow window
    still burns at 2.0 but the fast window is clean — multi-window AND
    means no breach (the 'pages hours after recovery' failure mode)."""
    doc = slo_lib.evaluate(_records(bad_ticks=(1, 2)),
                           specs=[_spec()], now_tick=100)
    s = doc["slos"][0]
    assert s["windows"]["fast"]["burn_rate"] == 0.0
    assert s["windows"]["slow"]["burn_rate"] == 2.0
    assert s["breach"] is False and doc["ok"]


def test_single_spike_does_not_page():
    """One bad tick inside the fast window only: fast burns hot (10.0)
    but the slow window sits at 1.0 < 2.0 — no breach (the 'one bad
    tick pages' failure mode)."""
    doc = slo_lib.evaluate(_records(bad_ticks=(100,)),
                           specs=[_spec()], now_tick=100)
    s = doc["slos"][0]
    assert s["windows"]["fast"]["burn_rate"] == 10.0
    assert s["windows"]["slow"]["burn_rate"] == 1.0
    assert s["breach"] is False


def test_error_rate_spec_counts_errors_only():
    spec = _spec(name="error_rate", metric="error", threshold_ms=None,
                 objective=0.95)
    # 1 error in the fast 10: (1/10)/0.05 = 2.0; slow: (1/100)/0.05
    # = 0.2 -> fast-only, no breach
    doc = slo_lib.evaluate(_records(error_ticks=(100,)), specs=[spec],
                           now_tick=100)
    s = doc["slos"][0]
    assert s["windows"]["fast"]["burn_rate"] == 2.0
    assert s["windows"]["slow"]["burn_rate"] == pytest.approx(0.2)
    assert s["breach"] is False
    # 10 errors spread across the slow window incl. 2 fast: breach
    doc = slo_lib.evaluate(
        _records(error_ticks=tuple(range(10, 101, 10))), specs=[spec],
        now_tick=100)
    s = doc["slos"][0]
    assert s["windows"]["slow"]["burn_rate"] == 2.0
    assert s["windows"]["fast"]["burn_rate"] == 2.0
    assert s["breach"] is True
    # an error is bad under LATENCY SLOs too (it delivered nothing)
    lat = slo_lib.evaluate(_records(error_ticks=(100,)),
                           specs=[_spec()], now_tick=100)
    assert lat["slos"][0]["windows"]["fast"]["bad"] == 1


def test_missing_measurement_counts_bad():
    """A retired request with no ttft recorded (torn stream) burns
    budget — absence of evidence must not look like health."""
    recs = _records(n=10)
    recs[-1]["ttft_ms"] = None
    doc = slo_lib.evaluate(recs, specs=[_spec()], now_tick=10)
    assert doc["slos"][0]["windows"]["fast"]["bad"] == 1


def test_empty_records_and_observed_p99():
    doc = slo_lib.evaluate([], specs=[_spec()])
    s = doc["slos"][0]
    assert doc["ok"] and s["breach"] is False
    assert s["windows"]["fast"]["requests"] == 0
    assert s["observed_p99_ms"] is None
    doc = slo_lib.evaluate(_records(bad_ticks=(95, 100)),
                           specs=[_spec()], now_tick=100)
    assert doc["slos"][0]["observed_p99_ms"] == 900.0
    json.dumps(doc, allow_nan=False)       # strict JSON end to end


# --- spec DSL --------------------------------------------------------------


def test_parse_specs():
    specs = slo_lib.parse_specs("")
    assert specs == list(slo_lib.DEFAULT_SLOS)
    specs = slo_lib.parse_specs(
        "ttft_p99_ms<=250, latency_p99_ms<=2000, error_rate<=0.05")
    assert [s.name for s in specs] == ["ttft_p99_ms",
                                       "latency_p99_ms", "error_rate"]
    assert specs[0].threshold_ms == 250.0
    assert specs[0].metric == "ttft_ms"
    assert specs[2].objective == pytest.approx(0.95)
    for bad in ("p99<=1", "ttft_p99_ms", "ttft_p99_ms<=abc",
                "ttft_p99_ms<=-5", "error_rate<=1.5"):
        with pytest.raises(ValueError):
            slo_lib.parse_specs(bad)


# --- span-stream plumbing + surfaces ---------------------------------------


def _write_spans(path, ttfts, lat_s=0.05, proc=0):
    """A minimal healthy stream: one request per ttft value, retiring
    one per tick."""
    rec = spans_lib.SpanRecorder(str(path), process_index=proc)
    for i, ttft in enumerate(ttfts):
        rec.emit("submit", rid=i, prompt_len=2, max_new_tokens=1,
                 arrival=0.0)
        rec.emit("admit", rid=i, pages_held=1, tick=i)
        rec.emit("prefill", rid=i, bucket=2, pages_width=1)
        rec.emit("first_token", rid=i, ttft_ms=ttft)
        rec.emit("retire", rid=i, generated=1, finish_t=lat_s,
                 tick=i + 1)
    rec.close()
    return rec.path


def test_records_from_spans(tmp_path):
    path = _write_spans(tmp_path, [10.0, 20.0])
    assert schema_lib.validate_span_file(path) == []
    recs = slo_lib.records_from_spans(spans_lib.read_spans(path))
    assert [r["ttft_ms"] for r in recs] == [10.0, 20.0]
    assert [r["retire_tick"] for r in recs] == [1, 2]
    assert all(r["latency_ms"] == 50.0 for r in recs)
    assert not any(r["error"] for r in recs)
    # an in-flight request (no terminal event) is excluded
    rows = spans_lib.read_spans(path)
    rows.append({"kind": "span", "v": schema_lib.SCHEMA_VERSION,
                 "t": 9.0, "proc": 0, "event": "submit", "rid": 77,
                 "prompt_len": 1, "max_new_tokens": 1,
                 "arrival": 0.0})
    assert len(slo_lib.records_from_spans(rows)) == 2
    # an errored request IS terminal
    rows.append({"kind": "span", "v": schema_lib.SCHEMA_VERSION,
                 "t": 9.1, "proc": 0, "event": "error", "rid": 77,
                 "reason": "boom"})
    recs = slo_lib.records_from_spans(rows)
    assert len(recs) == 3 and recs[-1]["error"] is True


def test_truncated_tail_heads_do_not_read_as_bad(tmp_path):
    """/slo reads bounded TAILS: a retire whose submit scrolled out of
    the tail is missing its measurements by truncation, not failure —
    it must be EXCLUDED, not counted bad (it used to fire false
    breaches on any long-running server)."""
    path = _write_spans(tmp_path, [10.0, 20.0])
    rows = spans_lib.read_spans(path)
    # simulate the tail window: drop rid 0's submit (the head)
    truncated = [r for r in rows
                 if not (r.get("rid") == 0 and r["event"] == "submit")]
    recs = slo_lib.records_from_spans(truncated)
    assert [r["rid"] for r in recs] == [1]        # rid 0 excluded
    doc = slo_lib.evaluate(recs, specs=[_spec(threshold_ms=50.0)])
    assert doc["ok"]


def test_observed_p99_matches_engine_percentile():
    """dtx_slo_observed_p99_ms and dtx_generate_ttft_p99_ms share ONE
    percentile definition (np.percentile, linear interpolation) —
    identical data must yield identical p99s across the two gauge
    families."""
    from distributed_tensorflow_example_tpu.serving.engine import (
        _percentile as engine_percentile,
    )

    vals = [100.0 * (i + 1) for i in range(10)]
    recs = [{"rid": i, "retire_tick": i + 1, "ttft_ms": v,
             "latency_ms": 1.0, "error": False}
            for i, v in enumerate(vals)]
    doc = slo_lib.evaluate(recs, specs=[_spec(threshold_ms=1e9)],
                           now_tick=10)
    assert doc["slos"][0]["observed_p99_ms"] == pytest.approx(
        engine_percentile(vals, 0.99))


def test_cli_slo_exit_codes(tmp_path, capsys):
    d = tmp_path / "run"
    d.mkdir()
    _write_spans(d, [10.0] * 8)
    # healthy under a generous spec
    assert cli_lib.main(["slo", str(d), "--spec",
                         "ttft_p99_ms<=50,latency_p99_ms<=100,"
                         "error_rate<=0.5"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] and doc["requests"] == 8
    # a doctored breach: every request violates the bound -> exit 3
    assert cli_lib.main(["slo", str(d), "--spec",
                         "ttft_p99_ms<=5"]) == 3
    out = capsys.readouterr()
    doc = json.loads(out.out)
    assert doc["breaches"] == ["ttft_p99_ms"]
    assert "BREACH" in out.err
    # no span stream -> 2; malformed spec -> 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli_lib.main(["slo", str(empty)]) == 2
    assert cli_lib.main(["slo", str(d), "--spec", "bogus"]) == 2


def test_slo_endpoint_and_prometheus_gauges(tmp_path):
    _write_spans(tmp_path, [10.0] * 5)
    specs = slo_lib.parse_specs(
        "ttft_p99_ms<=50,latency_p99_ms<=100,error_rate<=0.5")
    srv = serve_lib.StatusServer(str(tmp_path), slos=specs)
    port = srv.start(0)
    assert port
    try:
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["ok"] and [s["name"] for s in doc["slos"]] == [
            "ttft_p99_ms", "latency_p99_ms", "error_rate"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        lines = text.splitlines()
        assert 'dtx_slo_breach{slo="ttft_p99_ms"} 0' in lines
        assert ('dtx_slo_burn_rate{slo="ttft_p99_ms",window="fast"} 0'
                in lines)
        assert 'dtx_slo_observed_p99_ms{slo="ttft_p99_ms"} 10' in lines
        assert "dtx_slo_requests 5" in lines
        # every sample line still belongs to a # TYPE'd gauge family
        for ln in lines:
            if ln.startswith("#") or not ln:
                continue
            name = ln.split("{")[0].split(" ")[0]
            assert f"# TYPE {name} gauge" in lines
    finally:
        srv.close()


def test_prometheus_without_spans_has_no_slo_gauges(tmp_path):
    text = serve_lib.prometheus_text(
        serve_lib.collect_status(str(tmp_path)))
    assert "dtx_slo_" not in text


# --- typed terminals in the SLO fold (ISSUE 15) ---------------------------


def _vrow(event, rid=None, **f):
    row = {"kind": "span", "v": schema_lib.SCHEMA_VERSION, "t": 1.0,
           "proc": 0, "event": event, **f}
    if rid is not None:
        row["rid"] = rid
    return row


def _lifecycle(rid, tick, ttft=10.0):
    return [
        _vrow("submit", rid=rid, prompt_len=2, max_new_tokens=2,
              arrival=0.0),
        _vrow("admit", rid=rid, pages_held=1, tick=tick - 1),
        _vrow("first_token", rid=rid, ttft_ms=ttft),
        _vrow("retire", rid=rid, generated=2, finish_t=1.0,
              tick=tick),
    ]


def test_timeout_and_failed_terminals_burn_error_budget():
    """SLO error-rate treats timeout/failed as bad (the typed
    non-delivery terminals), closed-form: 2 bad of 4 terminals on a
    budget of 0.5 burns at exactly 1.0."""
    rows = _lifecycle(0, 1) + _lifecycle(1, 2)
    rows += [_vrow("submit", rid=2, prompt_len=2, max_new_tokens=9,
                   arrival=0.0),
             _vrow("timeout", rid=2, reason="deadline", tick=3,
                   generated=1)]
    rows += [_vrow("submit", rid=3, prompt_len=2, max_new_tokens=9,
                   arrival=0.0),
             _vrow("failed", rid=3, reason="budget", attempts=2)]
    recs = slo_lib.records_from_spans(rows)
    assert len(recs) == 4
    by_rid = {r["rid"]: r for r in recs}
    assert by_rid[2]["terminal"] == "timeout" and by_rid[2]["error"]
    assert by_rid[3]["terminal"] == "failed" and by_rid[3]["error"]
    spec = slo_lib.SLOSpec("error_rate", "error", None, objective=0.5,
                           fast_window=10, slow_window=10,
                           burn_threshold=1.0)
    doc = slo_lib.evaluate(recs, specs=[spec], now_tick=3)
    w = doc["slos"][0]["windows"]["fast"]
    assert w["requests"] == 4 and w["bad"] == 2
    assert w["burn_rate"] == 1.0
    assert doc["slos"][0]["breach"]


def test_shed_gets_its_own_rate_not_the_error_budget():
    """Shed requests are carved OUT of the SLO windows (a typed 503
    is policy, not breach) and reported as their own rate over the
    slow window — closed form: 2 shed of 6 terminals = 1/3."""
    rows = []
    for rid, tick in ((0, 1), (1, 2), (2, 3), (3, 4)):
        rows += _lifecycle(rid, tick)
    rows += [_vrow("shed", rid=10, reason="queue", tick=2, queued=4),
             _vrow("shed", rid=11, reason="queue", tick=3, queued=5)]
    recs = slo_lib.records_from_spans(rows)
    assert len(recs) == 6
    spec = slo_lib.SLOSpec("error_rate", "error", None,
                           objective=0.99, fast_window=10,
                           slow_window=10, burn_threshold=1.0)
    doc = slo_lib.evaluate(recs, specs=[spec], now_tick=4)
    # shed never enters the SLO windows...
    w = doc["slos"][0]["windows"]["fast"]
    assert w["requests"] == 4 and w["bad"] == 0
    assert doc["ok"] and doc["requests"] == 4
    # ...but gets its own rate section
    assert doc["shed"]["shed"] == 2
    assert doc["shed"]["terminals"] == 6
    assert doc["shed"]["rate"] == round(2 / 6, 6)
    # and the gauge rides /metrics via prometheus_text
    status = {"procs": {}, "live": False}
    text = serve_lib.prometheus_text(status, slo=doc)
    assert "dtx_slo_shed_rate 0.3333" in text


# --- federated SLO (ISSUE 16: fleet observability) ------------------------


def _src_records(source, n, bad, first_tick=1):
    """n requests for one fleet source retiring at consecutive ticks;
    the first ``bad`` of them blow the 500ms ttft bound."""
    return [{"rid": i, "proc": 0, "source": source,
             "terminal": "result",
             "retire_tick": first_tick + i,
             "ttft_ms": 900.0 if i < bad else 100.0,
             "latency_ms": 50.0, "error": False}
            for i in range(n)]


def test_fleet_identity_closed_form():
    """THE federated acceptance case: two sources, hand-counted bad
    fractions.  Because the per-source record sets partition the
    fleet set inside every shared-now_tick window, the fleet burn MUST
    equal the request-weighted recombination of the per-source burns —
    checked exactly (integer counts, one shared rounding), no
    tolerance."""
    spec = _spec(objective=0.9, fast_window=5, slow_window=20,
                 burn_threshold=100.0)      # verdict out of the way
    # a: 10 requests ticks 1..10, 2 bad; b: 6 requests ticks 5..10,
    # 1 bad — b's window occupancy differs from a's, so the identity
    # is not trivially "same counts everywhere"
    records = (_src_records("a", 10, 2)
               + _src_records("b", 6, 1, first_tick=5))
    doc = slo_lib.fleet_evaluate(records, specs=[spec])
    assert doc["kind"] == "fleet_slo_report"
    assert doc["sources"] == ["a", "b"]
    assert doc["now_tick"] == 10            # shared: max fleet-wide
    # slow window (ticks 1..10): all 16 requests, 3 bad
    fw = doc["fleet"]["slos"][0]["windows"]["slow"]
    assert fw["requests"] == 16 and fw["bad"] == 3
    assert fw["burn_rate"] == round((3 / 16) / 0.1, 6)
    aw = doc["per_source"]["a"]["slos"][0]["windows"]["slow"]
    bw = doc["per_source"]["b"]["slos"][0]["windows"]["slow"]
    assert (aw["requests"], aw["bad"]) == (10, 2)
    assert (bw["requests"], bw["bad"]) == (6, 1)
    assert aw["burn_rate"] == 2.0           # (2/10)/0.1
    # the identity: fleet == request-weighted per-source combination
    assert doc["identity"]["holds"] and doc["ok"]
    for chk in doc["identity"]["checks"]:
        assert chk["holds"], chk
        assert chk["fleet_bad"] == chk["sum_source_bad"]
        assert chk["fleet_requests"] == chk["sum_source_requests"]
        assert chk["fleet_burn"] == chk["recombined_burn"]
    # fast window (ticks 6..10): a contributes 5 requests 0 bad, b
    # contributes 5 (ticks 6..10) of which bad rid 0 (tick 5) is OUT
    fa = doc["per_source"]["a"]["slos"][0]["windows"]["fast"]
    fb = doc["per_source"]["b"]["slos"][0]["windows"]["fast"]
    ff = doc["fleet"]["slos"][0]["windows"]["fast"]
    assert (fa["requests"], fa["bad"]) == (5, 0)
    assert (fb["requests"], fb["bad"]) == (5, 0)
    assert (ff["requests"], ff["bad"]) == (10, 0)
    json.dumps(doc, allow_nan=False)        # strict JSON end to end


def test_fleet_shared_now_tick_not_per_source():
    """Per-source windows slide from the FLEET's newest tick, not each
    source's own — otherwise the partition property (and with it the
    identity) would silently break for a source that went quiet."""
    spec = _spec(objective=0.9, fast_window=3, slow_window=100,
                 burn_threshold=100.0)
    # a went quiet at tick 4; b is live through tick 10
    records = (_src_records("a", 4, 4)       # all bad, ticks 1..4
               + _src_records("b", 10, 0))
    doc = slo_lib.fleet_evaluate(records, specs=[spec])
    fa = doc["per_source"]["a"]["slos"][0]["windows"]["fast"]
    # fast window = ticks 8..10: a's records are ALL outside it
    assert fa["requests"] == 0 and fa["bad"] == 0
    assert doc["identity"]["holds"]


def test_fleet_source_falls_back_to_proc():
    """Records without a collector source stamp (a single-dir
    multi-proc run) federate per process index."""
    records = []
    for proc in (0, 1):
        for i in range(3):
            records.append({"rid": i, "proc": proc, "source": None,
                            "terminal": "result",
                            "retire_tick": i + 1, "ttft_ms": 100.0,
                            "latency_ms": 50.0, "error": False})
    doc = slo_lib.fleet_evaluate(records)
    assert doc["sources"] == ["proc0", "proc1"]
    assert doc["identity"]["holds"] and doc["ok"]
    assert doc["fleet"]["requests"] == 6


def test_fleet_records_from_spans_carry_source(tmp_path):
    """The span->record fold keeps the collector's source stamp, so
    fleet_evaluate over a merged stream groups correctly end to end
    — and sheds stay carved out of the identity's windows."""
    path = _write_spans(tmp_path, [10.0, 20.0])
    rows = spans_lib.read_spans(path)
    for r in rows:
        r["source"] = "siteA"
    rows.append({"kind": "span", "v": schema_lib.SCHEMA_VERSION,
                 "t": 9.0, "proc": 0, "event": "shed", "rid": 50,
                 "reason": "queue", "tick": 1, "queued": 9,
                 "source": "siteA"})
    recs = slo_lib.records_from_spans(rows)
    assert all(r["source"] == "siteA" for r in recs)
    doc = slo_lib.fleet_evaluate(
        recs, specs=[_spec(threshold_ms=50.0)])
    assert doc["sources"] == ["siteA"]
    assert doc["identity"]["holds"]
    # the shed record is out of the windows but in the shed section
    assert doc["fleet"]["requests"] == 2
    assert doc["fleet"]["shed"]["shed"] == 1
