"""bench.py driver-surface smoke tests.

Round 5's capture crashed with ``guarded() got multiple values for
argument 'name'`` (VERDICT r5) — an untested one-line edit that voided
the committed perf record from ``transformer_wide_long`` onward. These
tests pin the driver surface: ``main()`` must reach its final JSON
line on both the CPU and (stubbed) TPU row paths, and a tiny real
config must flow through the genuine capture machinery."""

import json

import pytest

import bench

from conftest import needs_stack  # noqa: E402

# every key main()'s headline block reads off the bench_config row
_FULL_ROW = {
    "wall_clock_20ep_s": 1.0, "wall_clock_min_s": 1.0,
    "wall_clock_max_s": 1.0, "cold_wall_clock_20ep_s": 1.0,
    "congestion_suspect": False, "repeats": 1,
    "examples_per_sec": 100.0, "examples_per_sec_per_chip": 100.0,
    "model_flops_per_step": 1.0, "mfu": 0.5, "test_accuracy": 0.9,
    "final_cost": 0.1, "devices": 1, "dataset": "synthetic",
}


def _stub_rows(monkeypatch):
    """Replace every bench_* row function with a cheap stub so main()'s
    plumbing (guarded calls, headline selection, final JSON) runs in
    milliseconds."""
    monkeypatch.setattr(
        bench, "bench_config",
        lambda name, cfg, epochs_full=20, repeats=5: dict(
            _FULL_ROW, config=name))
    monkeypatch.setattr(
        bench, "bench_learning_regime",
        lambda repeats=1: {"config": "learning_regime_lr0.5",
                           "test_accuracy": 0.9, "matches_cpu": True})
    monkeypatch.setattr(
        bench, "bench_real_mnist",
        lambda repeats=1: {"config": "real_mnist_parity",
                           "skipped": "stubbed: no real MNIST"})
    monkeypatch.setattr(
        bench, "bench_input_pipeline",
        lambda repeats=3: {"config": "input_pipeline",
                           "blocking_step_ms": 10.0,
                           "prefetch_step_ms": 9.0,
                           "overlap_ratio": 1.1111,
                           "prefetch_not_slower": True,
                           "test_accuracy": 0.9})
    for name in ("bench_reference_device_program", "bench_mxu",
                 "bench_pallas_parity", "bench_flash_attention",
                 "bench_ring_flash", "bench_transformer",
                 "bench_pipeline_bubble",
                 "bench_moe_dispatch", "bench_lm"):
        monkeypatch.setattr(
            bench, name,
            lambda *a, _n=name, **kw: {"config": _n})
    # the decode row (r9): tok/s plus the HBM roofline — main() must
    # carry decode_hbm_frac onto the final line under its gate name
    monkeypatch.setattr(
        bench, "bench_decode",
        lambda *a, **kw: {"config": "decode_throughput",
                          "tokens_per_sec": 26900.0,
                          "decode_step_ms": 1.19,
                          "decode_bytes_per_step": 3.2e8,
                          "decode_achieved_gbps": 270.0,
                          "decode_hbm_frac": 0.33,
                          "decode_hbm_frac_int8_projected": 0.21})
    # the kv-quant row (r11) runs on EVERY backend: the int8-KV
    # closed forms are the gated evidence and must reach the final
    # line off-TPU too (the pp_memory lesson)
    monkeypatch.setattr(
        bench, "bench_kv_quant",
        lambda *a, **kw: {"config": "kv_quant",
                          "decode_kv_bytes_per_step": 2.68e8,
                          "decode_kv_bytes_per_step_int8": 1.34e8,
                          "decode_kv_scale_bytes_per_step": 4.2e6,
                          "decode_kv_reduction_int8": 2.0,
                          "kv_quant_tok_s_base": 1196.3,
                          "kv_quant_tok_s_int8": 1432.3,
                          "kv_quant_greedy_match": True})
    # the checkpoint row (r13) runs on EVERY backend: the write-
    # behind stall + overhead A/B must reach the final line under
    # their gate names
    monkeypatch.setattr(
        bench, "bench_checkpoint",
        lambda *a, **kw: {"config": "checkpoint",
                          "nockpt_step_ms": 5.2,
                          "ckpt_step_ms": 5.6,
                          "ckpt_overhead_ratio": 1.0769,
                          "ckpt_stall_ms": 1.05,
                          "ckpt_write_ms": 42.0,
                          "ckpt_snapshots": 6,
                          "ckpt_snapshots_coalesced": 2,
                          "ckpt_objects_written": 50,
                          "ckpt_objects_reused": 10,
                          "ckpt_reuse_frac": 0.1667,
                          "ckpt_bytes_written": 9999,
                          "ckpt_state_bytes": 5308416,
                          "ckpt_snapshots_per_run": 12})
    # the serving row (r9) runs on EVERY backend: analytic
    # continuous-vs-static tick accounting + the measured engine sweep
    monkeypatch.setattr(
        bench, "bench_serving",
        lambda *a, **kw: {"config": "serving",
                          "continuous_ticks": 53,
                          "static_ticks": 85,
                          "tick_speedup_continuous_vs_static": 1.604,
                          "continuous_beats_static": True,
                          "cache_occupancy_frac": 0.35,
                          "serving_p50_ms": 109.3,
                          "serving_p99_ms": 214.2,
                          "serving_tok_s": 950.1,
                          "serving_requests": 24})
    # the degraded-serving row (r15) runs on EVERY backend: the
    # analytic deadline/shed accounting + the supervision A/B must
    # reach the final line under the gate names
    monkeypatch.setattr(
        bench, "bench_serving_degraded",
        lambda *a, **kw: {"config": "serving_degraded",
                          "degraded_sim_ticks": 35,
                          "degraded_completed_sim": 16,
                          "degraded_shed_sim": 4,
                          "degraded_timeout_sim": 4,
                          "serving_degraded_completed_frac": 0.666667,
                          "terminates_typed": True,
                          "supervised_completed": 12,
                          "unsupervised_completed": 0,
                          "supervision_recovers": True,
                          "serving_degraded_p99_ms": 512.5})
    # the fleet-failover row (r18) runs on EVERY backend: the analytic
    # router completed fraction + failover p99 are the gated evidence
    # and must reach the final line under their gate names
    monkeypatch.setattr(
        bench, "bench_fleet_failover",
        lambda *a, **kw: {"config": "fleet_failover",
                          "fleet_failover_requests": 12,
                          "fleet_completed_frac": 0.916667,
                          "fleet_analytic_failovers": 3,
                          "fleet_breaker_opened": True,
                          "terminates_typed": True,
                          "fleet_failover_p99_ms": 3264.91,
                          "fleet_beats_routerless": True})
    # the workload-replay row (r19) runs on EVERY backend: the
    # two-replay determinism fraction + the capacity forecast gap are
    # the gated evidence and must reach the final line gate-named
    monkeypatch.setattr(
        bench, "bench_workload_replay",
        lambda *a, **kw: {"config": "workload_replay",
                          "workload_replay_requests": 16,
                          "workload_id": "wl-stubstubstub",
                          "replay_identical": True,
                          "replay_determinism_frac": 1.0,
                          "capacity_forecast_qps": 0.34,
                          "capacity_measured_qps": 0.3402,
                          "capacity_forecast_rel_err": 0.000588,
                          "capacity_knee_speed": 8.0,
                          "capacity_required_replicas": 3,
                          "terminates_typed": True})
    # the span-overhead row (r16) runs on EVERY backend: the
    # interleaved spans-on/off ratio is the gated evidence that
    # tracing is effectively free and must reach the final line
    monkeypatch.setattr(
        bench, "bench_trace_overhead",
        lambda *a, **kw: {"config": "trace_overhead",
                          "trace_off_tok_s": 5012.4,
                          "trace_on_tok_s": 4983.9,
                          "trace_retained_tok_frac": 0.9943,
                          "trace_overhead_frac": 0.0057,
                          "trace_spans_emitted": 480,
                          "trace_rounds": 5})
    # the latency-attribution row (r17) runs on EVERY backend: the
    # waterfall sum-to-wall residual + the attribution-overhead A/B
    # are gated and must reach the final line under their gate names
    monkeypatch.setattr(
        bench, "bench_latency_attribution",
        lambda *a, **kw: {"config": "latency_attribution",
                          "waterfall_requests": 12,
                          "waterfall_complete": 12,
                          "waterfall_terminals": {"result": 5,
                                                  "timeout": 1,
                                                  "shed": 6},
                          "waterfall_sum_to_wall_frac": 1.0,
                          "waterfall_max_residual_frac": 0.0,
                          "waterfall_sum_to_wall_ok": True,
                          "waterfall_wall_p99_ms": 152.1,
                          "littles_law_rel_err": 0.0,
                          "littles_law_holds": True,
                          "attribution_off_tok_s": 5012.4,
                          "attribution_on_tok_s": 4997.1,
                          "attribution_retained_tok_frac": 0.9969,
                          "attribution_overhead_frac": 0.0031,
                          "attribution_rounds": 5})
    # the multi-site local-SGD row (r10) runs on EVERY backend: the
    # analytic comm-volume keys + the measured A/B must reach the
    # final line under their gate names
    monkeypatch.setattr(
        bench, "bench_local_sgd",
        lambda *a, **kw: {"config": "local_sgd",
                          "n_params": 79424,
                          "sync_comm_bytes_per_step": 555968.0,
                          "local_sgd_outer_sync_bytes": 555968.0,
                          "sync_comm_bytes_per_token": 135.734,
                          "local_sgd_comm_bytes_per_token": 16.967,
                          "local_sgd_comm_bytes_per_token_h64": 2.121,
                          "comm_reduction_h8": 8.0,
                          "comm_reduction_h64": 64.0,
                          "inner_steps_gated": 8,
                          "local_sgd_outer_quant_sync_bytes": 139202.0,
                          "local_sgd_outer_quant_bytes_per_token": 4.248,
                          "local_sgd_outer_quant_reduction": 3.99,
                          "sync_step_ms": 144.6, "sync_final_cost": 4.31,
                          "local_sgd_step_ms": 115.5,
                          "local_sgd_final_cost": 4.16,
                          "final_cost_ratio": 0.966,
                          "outer_quant_step_ms": 115.8,
                          "outer_quant_final_cost": 4.16,
                          "outer_quant_cost_ratio": 1.0})
    # the pp_memory row runs on EVERY backend (r8 bubble bench): its
    # analytic bubble-fraction keys must reach the final line as
    # pp_bubble_frac_* so --gate can hold the schedule
    monkeypatch.setattr(
        bench, "bench_pp_memory",
        lambda *a, **kw: {"config": "pp_memory",
                          "gpipe_measured_ticks": 57.0,
                          "gpipe_ideal_ticks": 48.0,
                          "gpipe_bubble_fraction": 0.1579,
                          "1f1b_measured_ticks": 57.0,
                          "1f1b_ideal_ticks": 48.0,
                          "1f1b_bubble_fraction": 0.1579,
                          "interleaved_v2_measured_ticks": 52.5,
                          "interleaved_v2_ideal_ticks": 48.0,
                          "interleaved_v2_bubble_fraction": 0.0857,
                          "interleaved_v4_measured_ticks": 50.25,
                          "interleaved_v4_ideal_ticks": 48.0,
                          "interleaved_v4_bubble_fraction": 0.0448})
    # the fused-kernel rows (ISSUE 6): transformer_wide carries its
    # per-variant MFUs + headline, moe_wide carries the grouped A/B
    # AND the dispatch-vs-expert breakdown — main() must forward the
    # breakdown + headline MFU onto the final line for --gate
    monkeypatch.setattr(
        bench, "bench_transformer_wide",
        lambda *a, **kw: {"config": "transformer_wide",
                          "dense_mfu": 0.5, "flash_mfu": 0.55,
                          "fused_ln_mfu": 0.62, "fp8_ffn_mfu": 0.66,
                          "mfu": 0.66, "target_mfu": 0.60})
    monkeypatch.setattr(
        bench, "bench_moe_wide",
        lambda *a, **kw: {"config": "moe_wide", "mfu": 0.38,
                          "grouped_mfu": 0.36, "fp8_mfu": 0.38,
                          "fp8_step_time_ms": 90.0,
                          "fp8_tokens_per_sec": 1100.0,
                          "target_mfu": 0.35,
                          "tokens_per_sec": 1000.0,
                          "moe_dispatch_ms": 12.5, "moe_expert_ms": 40.0,
                          "moe_expert_grouped_ms": 30.0})
    # transformer_wide_long is the r5 crash site: main() passes name=
    # through guarded(), which must deliver it as a row kwarg
    monkeypatch.setattr(
        bench, "bench_transformer_wide_long",
        lambda *a, **kw: {"config": kw.get("name",
                                           "transformer_wide_long")})


def test_bench_main_cpu_stubbed(monkeypatch, capsys):
    """Default CPU arg path reaches rc=0 and the final JSON line with
    the driver-read keys."""
    _stub_rows(monkeypatch)
    rc = bench.main([])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    final = json.loads(out[-1])
    assert final["metric"] == "mnist_20epoch_wall_clock"
    for key in ("value", "unit", "vs_baseline", "config", "real_mnist"):
        assert key in final, key
    assert final["real_mnist"] == "skipped"
    # the input-pipeline gate keys ride the final line (dtx-obs
    # compare reads them off a BENCH capture via extract_metrics)
    assert final["input_pipeline_blocking_step_ms"] == 10.0
    assert final["input_pipeline_prefetch_step_ms"] == 9.0
    assert final["input_pipeline_overlap_ratio"] == 1.1111
    # the r8 bubble-fraction carriage: analytic tick-table keys from
    # the pp_memory row reach the final line on the CPU path too
    assert final["pp_bubble_frac_gpipe"] == 0.1579
    assert final["pp_bubble_frac_1f1b"] == 0.1579
    assert final["pp_bubble_frac_interleaved_v2"] == 0.0857
    assert final["pp_bubble_frac_interleaved_v4"] == 0.0448
    # the r9 serving carriage (every backend): the gate keys + the
    # analytic continuous-vs-static evidence reach the final line
    assert final["serving_p99_ms"] == 214.2
    assert final["serving_tok_s"] == 950.1
    assert final["serving_tick_speedup"] == 1.604
    # the r15 degraded-serving carriage (every backend): analytic
    # completed fraction + supervised p99 + the A/B verdict
    assert final["serving_degraded_completed_frac"] == 0.666667
    assert final["serving_degraded_p99_ms"] == 512.5
    assert final["supervision_recovers"] is True
    # the r18 fleet-failover carriage (every backend): the gated
    # completed fraction + failover p99 + the router-less A/B verdict
    assert final["fleet_completed_frac"] == 0.916667
    assert final["fleet_failover_p99_ms"] == 3264.91
    assert final["fleet_beats_routerless"] is True
    # the r19 workload-replay carriage (every backend): two-replay
    # determinism + the capacity forecast gap, gate-named, plus the
    # identity verdict bit
    assert final["replay_determinism_frac"] == 1.0
    assert final["capacity_forecast_rel_err"] == 0.000588
    assert final["replay_identical"] is True
    assert final["serving_continuous_beats_static"] is True
    # the r10 multi-site carriage (every backend): the analytic H=8
    # comm bytes/token + reductions + the measured final-cost A/B
    assert final["local_sgd_comm_bytes_per_token"] == 16.967
    assert final["local_sgd_comm_reduction_h8"] == 8.0
    assert final["local_sgd_comm_reduction_h64"] == 64.0
    assert final["local_sgd_final_cost"] == 4.16
    assert final["local_sgd_sync_final_cost"] == 4.31
    # the r11 quantized-outer carriage (every backend): the int8+EF
    # closed forms + the measured quantized final cost, gate-named
    assert final["local_sgd_outer_quant_bytes_per_token"] == 4.248
    assert final["local_sgd_outer_quant_reduction"] == 3.99
    assert final["local_sgd_outer_quant_final_cost"] == 4.16
    # the r11 int8-KV carriage runs on the CPU path too (the gated
    # closed forms must not hide behind the TPU-only decode row)
    assert final["decode_kv_bytes_per_step_int8"] == 1.34e8
    assert final["decode_kv_reduction_int8"] == 2.0
    assert final["kv_quant_greedy_match"] is True
    # the r13 async-checkpoint carriage (every backend): submit stall
    # + the with/without step ratio, gate-named, plus the incremental
    # store's reuse evidence
    assert final["ckpt_stall_ms"] == 1.05
    assert final["ckpt_overhead_ratio"] == 1.0769
    assert final["ckpt_reuse_frac"] == 0.1667
    # the r16 span-overhead carriage (every backend): the gate key +
    # its complement reach the final line so --gate holds the <= 1%
    # tracing-cost claim over time
    assert final["trace_retained_tok_frac"] == 0.9943
    assert final["trace_overhead_frac"] == 0.0057
    # the r17 latency-attribution carriage (every backend): the
    # sum-to-wall residual + the attribution-overhead A/B, gate-named
    assert final["waterfall_sum_to_wall_frac"] == 1.0
    assert final["waterfall_max_residual_frac"] == 0.0
    assert final["attribution_retained_tok_frac"] == 0.9969
    assert final["attribution_overhead_frac"] == 0.0031


def test_bench_main_all_configs_stubbed(monkeypatch, capsys):
    _stub_rows(monkeypatch)
    rc = bench.main(["--all-configs"])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    final = json.loads(out[-1])
    assert final["config"] == "8way_dp"  # --all-configs headline row
    assert "real_mnist" in final


def test_bench_main_tpu_rows_no_guarded_collision(monkeypatch, capsys):
    """The FULL TPU row sweep — including the s16k call that forwards
    ``name=`` through guarded() — completes with rc=0. This exact call
    crashed round 5's capture (``guarded() got multiple values for
    argument 'name'``)."""
    import jax

    class _FakeTpu:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    _stub_rows(monkeypatch)
    monkeypatch.setattr(jax, "devices", lambda *a, **kw: [_FakeTpu()])
    rc = bench.main([])
    captured = capsys.readouterr()
    assert rc == 0, captured.err
    final = json.loads(captured.out.strip().splitlines()[-1])
    assert final["metric"] == "mnist_20epoch_wall_clock"
    assert "real_mnist" in final
    # the name= kwarg reached the s16k row function instead of
    # colliding inside guarded(): its row was emitted, not an error
    rows = [json.loads(ln) for ln in
            captured.err.strip().splitlines() if ln.startswith("{")]
    s16k = [r for r in rows
            if r.get("config") == "transformer_wide_long_s16k"]
    assert s16k and "error" not in s16k[0]
    # the fused-kernel gate keys ride the final line (obs.compare
    # extract_metrics reads them off a BENCH capture by these names)
    assert final["moe_dispatch_ms"] == 12.5
    assert final["moe_expert_ms"] == 40.0
    # the r9 decode-roofline carriage (TPU row): achieved-vs-peak HBM
    # bytes/s reaches the final line under its gate name
    assert final["decode_tokens_per_sec"] == 26900.0
    assert final["decode_hbm_frac"] == 0.33
    assert final["decode_achieved_gbps"] == 270.0
    assert final["serving_p99_ms"] == 214.2
    # the r11 int8-KV carriage (from the every-backend kv_quant row)
    assert final["decode_kv_bytes_per_step_int8"] == 1.34e8
    assert final["decode_kv_reduction_int8"] == 2.0
    assert final["kv_quant_greedy_match"] is True
    # the r11 fp8 headline: the best moe_wide/transformer_wide variant
    # (fp8 in the stubs) carries the row mfu the gate reads
    assert final["transformer_wide_mfu"] == 0.66
    assert final["moe_wide_mfu"] == 0.38


def test_bench_history_appends_final_summary(monkeypatch, capsys,
                                             tmp_path):
    """--history: the run's final summary lands in the rolling
    history.jsonl reduced to its gate metrics — the trajectory grows
    by exactly one entry per run."""
    from distributed_tensorflow_example_tpu.obs import (
        history as hist_lib,
    )

    _stub_rows(monkeypatch)
    hist = tmp_path / "history.jsonl"
    assert bench.main(["--history", str(hist)]) == 0
    capsys.readouterr()
    entries = hist_lib.read_history(str(hist))
    assert len(entries) == 1
    assert entries[0]["source"] == "bench"
    assert entries[0]["metrics"]["wall_s"] == 1.0   # the stub headline
    assert entries[0]["metrics"]["mfu"] == 0.5
    assert bench.main(["--history", str(hist)]) == 0
    capsys.readouterr()
    assert len(hist_lib.read_history(str(hist))) == 2


def test_bench_gate_rolling_exit_codes(monkeypatch, capsys, tmp_path):
    """--gate-rolling N: 0 against a same-speed history, 3 against a
    doctored faster one (with the verdict printed strictly AFTER the
    final summary line), 2 on an empty history — and the regressing
    run is still recorded."""
    from distributed_tensorflow_example_tpu.obs import (
        history as hist_lib,
    )

    _stub_rows(monkeypatch)
    hist = tmp_path / "history.jsonl"
    # empty history: unusable gate (2), but the run IS recorded
    assert bench.main(["--history", str(hist),
                       "--gate-rolling", "5"]) == 2
    out = capsys.readouterr().out.strip().splitlines()
    assert "gate_error" in json.loads(out[-1])
    assert len(hist_lib.read_history(str(hist))) == 1
    # same-speed history: pass
    assert bench.main(["--history", str(hist),
                       "--gate-rolling", "5"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    verdict = json.loads(out[-1])
    assert verdict["ok"] is True and verdict["gate_rolling"] == 5
    assert verdict["baseline_entries"] == 1     # the prior entry only
    # doctor a 2x-faster round into the history: rolling median halves
    # -> wall_s regression, exit 3, evidence order preserved
    for _ in range(3):
        hist_lib.append_entry(
            str(hist), {"metric": "x", "value": 0.5, "mfu": 0.5},
            label="doctored", source="test")
    assert bench.main(["--history", str(hist),
                       "--gate-rolling", "3"]) == 3
    out = capsys.readouterr().out.strip().splitlines()
    verdict = json.loads(out[-1])
    assert "wall_s" in verdict["regressions"]
    final = json.loads(out[-2])                 # summary precedes it
    assert final["metric"] == "mnist_20epoch_wall_clock"
    # the regressing run still landed in the trajectory
    assert hist_lib.read_history(str(hist))[-1]["source"] == "bench"


def test_bench_gate_rolling_requires_history(monkeypatch, capsys):
    _stub_rows(monkeypatch)
    with pytest.raises(SystemExit) as ei:
        bench.main(["--gate-rolling", "5"])
    assert ei.value.code == 2


def test_guarded_isolates_row_failures(monkeypatch, capsys):
    """guarded()'s contract: a raising row emits an error row instead
    of killing the sweep."""
    _stub_rows(monkeypatch)

    def boom(repeats=1):
        raise RuntimeError("synthetic row failure")

    monkeypatch.setattr(bench, "bench_learning_regime", boom)
    rc = bench.main([])
    assert rc == 0
    err_rows = [json.loads(ln) for ln in
                capsys.readouterr().err.strip().splitlines()]
    bad = [r for r in err_rows if r.get("config") == "learning_regime_lr0.5"]
    assert bad and "synthetic row failure" in bad[0]["error"]


@needs_stack
def test_bench_tiny_real_run(monkeypatch, capsys):
    """An --epochs 1 tiny config through the GENUINE capture machinery
    (bench_config -> _run -> train.loop.run): the final JSON line
    parses and carries the expected keys — the regression test whose
    absence let round 5's record vanish."""
    real_bench_config = bench.bench_config

    def tiny_bench_config(name, cfg, epochs_full=20, repeats=5):
        cfg = cfg.replace(dataset="synthetic", synthetic_train_size=512,
                          synthetic_test_size=128, batch_size=64,
                          compilation_cache="")
        return real_bench_config(name, cfg, epochs_full=epochs_full,
                                 repeats=1)

    monkeypatch.setattr(bench, "bench_config", tiny_bench_config)
    # the auxiliary rows each train a full config; keep the smoke test
    # to ONE real capture path
    monkeypatch.setattr(
        bench, "bench_learning_regime",
        lambda repeats=1: {"config": "learning_regime_lr0.5",
                           "test_accuracy": 0.9})
    monkeypatch.setattr(
        bench, "bench_real_mnist",
        lambda repeats=1: {"config": "real_mnist_parity",
                           "skipped": "stubbed for smoke test"})
    rc = bench.main(["--epochs", "1", "--repeats", "1"])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    final = json.loads(out[-1])
    for key in ("metric", "value", "unit", "vs_baseline", "config",
                "real_mnist", "mfu"):
        assert key in final, key
    assert final["metric"] == "mnist_20epoch_wall_clock"
    assert final["config"] == "reference_default"
    assert final["value"] > 0
