"""Chief-only download under jax.distributed (the multi-process arm of
data.mnist.load_datasets): with 2 real OS processes sharing a data_dir,
only process 0 downloads, both barrier, both parse — and the mirror
sees each archive exactly once."""

import gzip
import hashlib
import http.server
import json
import os
import socket
import struct
import subprocess
import sys
import threading

import numpy as np

from distributed_tensorflow_example_tpu.data import mnist as M

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
cfg = json.load(open(sys.argv[1]))
jax.distributed.initialize(
    coordinator_address=cfg["coord"], num_processes=2,
    process_id=int(sys.argv[2]),
)
from distributed_tensorflow_example_tpu.data import download as D
from distributed_tensorflow_example_tpu.data import mnist as M
D.MNIST_FILES = cfg["digests"]          # fixture archives, not canonical
M.VALIDATION_SIZE = 2
ds = M.load_datasets(cfg["data_dir"], dataset="mnist",
                     mirrors=tuple(cfg["mirrors"]))
assert ds.source == "mnist"
assert ds.train.num_examples == 6, ds.train.num_examples
print(f"proc {jax.process_index()} ok")
jax.distributed.shutdown()
"""


def _tiny_archives():
    rng = np.random.RandomState(0)

    def images(n):
        pix = rng.randint(0, 256, size=(n, 28, 28), dtype=np.uint8)
        return struct.pack(">IIII", M.IMAGE_MAGIC, n, 28, 28) + pix.tobytes()

    def labels(n):
        lab = rng.randint(0, 10, size=n).astype(np.uint8)
        return struct.pack(">II", M.LABEL_MAGIC, n) + lab.tobytes()

    return {
        M.TRAIN_IMAGES + ".gz": gzip.compress(images(8)),
        M.TRAIN_LABELS + ".gz": gzip.compress(labels(8)),
        M.TEST_IMAGES + ".gz": gzip.compress(images(4)),
        M.TEST_LABELS + ".gz": gzip.compress(labels(4)),
    }


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_chief_only_download(tmp_path):
    files = _tiny_archives()
    hits: list = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            name = self.path.rsplit("/", 1)[-1]
            hits.append(name)
            payload = files.get(name)
            if payload is None:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    data_dir = tmp_path / "mnist"
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps({
        "coord": f"127.0.0.1:{_free_port()}",
        "data_dir": str(data_dir),
        "mirrors": [f"http://127.0.0.1:{srv.server_address[1]}/mnist/"],
        "digests": {k: hashlib.sha256(v).hexdigest() for k, v in files.items()},
    }))
    script = tmp_path / "child.py"
    script.write_text(_CHILD)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(cfg_path), str(i)],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        try:
            outs = [p.communicate(timeout=240)[0] for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out[-3000:]
        assert "proc 0 ok" in outs[0] and "proc 1 ok" in outs[1]
    finally:
        srv.shutdown()
        srv.server_close()

    # each archive fetched exactly once (chief-only; worker barriered)
    assert sorted(hits) == sorted(files.keys()), hits
