"""Fleet span collector acceptance (ISSUE 16 tentpole).

Two halves, the serving-suite discipline:

- **pure Python**: source discovery, the clock-skew alignment golden
  (two sources ±5s apart, merged order pinned row by row), rotation
  stitching, the Chrome trace-event export validated against the
  format's event schema, and fleet-report exactly-once verdicts over
  doctored streams;
- **engine** (CPU jax): THE acceptance case — a 3-engine fleet with
  one engine crashed mid-decode by a FaultPlan, merged into a single
  timeline, every accepted request reconstructing fleet-wide to
  exactly one typed terminal with its trace_id chain unbroken across
  the supervised restart.
"""

import json
import os

import pytest

from distributed_tensorflow_example_tpu.obs import collector as col_lib
from distributed_tensorflow_example_tpu.obs import schema as schema_lib
from distributed_tensorflow_example_tpu.obs import slo as slo_lib
from distributed_tensorflow_example_tpu.obs import spans as spans_lib
from distributed_tensorflow_example_tpu.serving import scheduler as sl


def _row(event, t, rid=None, **f):
    row = {"kind": "span", "v": schema_lib.SCHEMA_VERSION, "t": t,
           "proc": 0, "event": event, **f}
    if rid is not None:
        row["rid"] = rid
    return row


def _write_rows(d, rows, proc=0):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"spans.{proc}.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return d


def _lifecycle(t0, rid, tid=None, dt=0.1):
    """One complete request starting at t0, milestones dt apart."""
    extra = {"trace_id": tid} if tid else {}
    return [
        _row("submit", t0, rid=rid, prompt_len=2, max_new_tokens=2,
             arrival=0.0, **extra),
        _row("admit", t0 + dt, rid=rid, pages_held=1, tick=0, **extra),
        _row("first_token", t0 + 2 * dt, rid=rid, ttft_ms=10.0,
             **extra),
        _row("retire", t0 + 3 * dt, rid=rid, generated=2,
             finish_t=0.05, tick=2, **extra),
    ]


# --- discovery -------------------------------------------------------------


def test_discover_sources_run_dirs_and_parents(tmp_path):
    a = _write_rows(str(tmp_path / "fleet" / "a"), _lifecycle(1.0, 0))
    b = _write_rows(str(tmp_path / "fleet" / "b"), _lifecycle(1.0, 0))
    (tmp_path / "fleet" / "not_a_run").mkdir()    # no streams: skipped
    # a run dir itself
    assert col_lib.discover_sources([a]) == [("a", a)]
    # a parent of run dirs, sorted by name, streamless child skipped
    assert col_lib.discover_sources([str(tmp_path / "fleet")]) == [
        ("a", a), ("b", b)]
    # a duplicate path never yields a duplicate source
    assert len(col_lib.discover_sources([a, a])) == 1
    # basename collision across parents disambiguates with #N
    c = _write_rows(str(tmp_path / "other" / "a"), _lifecycle(1.0, 0))
    names = [n for n, _ in col_lib.discover_sources([a, c])]
    assert names == ["a", "a#1"]
    # a restarts.jsonl alone marks a run dir too
    r = str(tmp_path / "restart_only")
    os.makedirs(r)
    with open(os.path.join(r, "restarts.jsonl"), "w") as f:
        f.write("{}\n")
    assert col_lib.discover_sources([r]) == [("restart_only", r)]
    assert col_lib.discover_sources([str(tmp_path / "ghost")]) == []


# --- clock-skew alignment (the golden) -------------------------------------


def test_clock_skew_alignment_golden(tmp_path):
    """Two sources started concurrently, wall clocks 5s apart: the
    per-source constant offset puts them on one axis and the merged
    order is pinned row by row — intra-source order untouched, the
    applied skew reported, never silent."""
    # a's clock: rows at 1000.0 / 1000.2 / 1000.4
    a = _write_rows(str(tmp_path / "a"), [
        _row("submit", 1000.0, rid=0, prompt_len=2, max_new_tokens=1,
             arrival=0.0),
        _row("admit", 1000.2, rid=0, pages_held=1, tick=0),
        _row("retire", 1000.4, rid=0, generated=1, finish_t=0.4,
             tick=1),
    ])
    # b's clock runs 5s AHEAD: same three milestones, emitted at
    # +0.1/+0.5 of its own start
    b = _write_rows(str(tmp_path / "b"), [
        _row("submit", 1005.0, rid=0, prompt_len=2, max_new_tokens=1,
             arrival=0.0),
        _row("admit", 1005.1, rid=0, pages_held=1, tick=0),
        _row("retire", 1005.5, rid=0, generated=1, finish_t=0.5,
             tick=1),
    ])
    col = col_lib.collect([a, b])
    skews = {s["source"]: s["skew_s"] for s in col["sources"]}
    assert skews == {"a": 0.0, "b": 5.0}    # reported, never silent
    # the pinned merged order: both starts align on t=1000.0 (stable
    # sort keeps source order for the tie), then b's admit at 1000.1,
    # a's admit at 1000.2, a's retire at 1000.4, b's retire at 1000.5
    order = [(r["source"], r["event"]) for r in col["rows"]]
    assert order == [("a", "submit"), ("b", "submit"),
                     ("b", "admit"), ("a", "admit"),
                     ("a", "retire"), ("b", "retire")]
    ts = [r["t"] for r in col["rows"]]
    assert ts == sorted(ts)
    assert ts[0] == 1000.0 and ts[-1] == pytest.approx(1000.5)
    # procs rewritten globally unique (both sources wrote proc 0)
    assert {(r["source"], r["proc"]) for r in col["rows"]} == {
        ("a", 0), ("b", 1)}
    # both requests reconstruct as distinct records from the merge
    recs = spans_lib.reconstruct(col["rows"])
    assert len(recs) == 2
    assert all(r["complete"] for r in recs.values())
    # --no-align: raw clocks kept, skew reported as 0 (not applied)
    raw = col_lib.collect([a, b], align=False)
    assert all(s["skew_s"] == 0.0 for s in raw["sources"])
    assert [r["t"] for r in raw["rows"]][-1] == 1005.5


def test_collect_stitches_rotated_streams(tmp_path):
    """A source whose span stream rotated mid-run merges whole: the
    collector sees every row across the .K…
    .1 segments."""
    d = str(tmp_path / "rot")
    rec = spans_lib.SpanRecorder(d, rotate_bytes=600, keep=10)
    s = sl.ContinuousScheduler(num_pages=5, page_size=4, max_batch=4,
                               recorder=rec)
    sl.simulate(s, [(0, 4, 4), (1, 4, 4), (2, 4, 4)])
    rec.close()
    assert os.path.exists(rec.path + ".1")
    col = col_lib.collect([d])
    assert col["sources"][0]["rows"] == len(
        spans_lib.read_spans(rec.path))
    recs = spans_lib.reconstruct(col["rows"])
    assert set(r for _p, r in recs) == {0, 1, 2}
    assert all(r["complete"] for r in recs.values())


# --- Chrome trace-event export ---------------------------------------------


def test_chrome_trace_golden(tmp_path):
    """The export validates against the Chrome trace-event schema:
    every event carries ph/pid/tid/name/ts, X events a dur, i events
    a scope, M events name their source track; request lifecycles
    nest (same tid, contained intervals); training phases and
    restarts land on the phase track."""
    tid = "ab" * 16
    rows = [dict(r, source="siteA")
            for r in _lifecycle(1.0, 0, tid=tid)]
    rows.append(dict(_row("phase", 2.0, phase="round", trace_id=tid,
                          dur_ms=100.0, step=3), source="siteA"))
    rows.append(dict(_row("engine_restart", 2.5, restart=1,
                          reason="crash", rids=[0], tick=1),
                     source="siteA"))
    rows.append({"kind": "restart", "t": 2.6, "proc": 0,
                 "event": "engine_restart", "source": "siteA"})
    doc = col_lib.chrome_trace(rows)
    json.dumps(doc, allow_nan=False)                # strict JSON
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["sources"] == ["siteA"]
    events = doc["traceEvents"]
    for e in events:
        assert e["ph"] in ("M", "X", "i"), e
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str)
        if e["ph"] == "M":
            assert e["name"] == "process_name"
            assert e["args"]["name"] == "siteA"
        else:
            assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 1.0
        if e["ph"] == "i":
            assert e["s"] == "p"
    by_name = {e["name"]: e for e in events}
    # the request span carries its trace context and terminal
    req = by_name["request 0"]
    assert req["cat"] == "request" and req["tid"] == 1
    assert req["args"]["trace_id"] == tid
    assert req["args"]["terminal"] == "result"
    assert req["ts"] == 1.0e6 and req["dur"] == pytest.approx(3.0e5)
    # lifecycle phases nest: same tid, contained in [ts, ts+dur]
    for name in ("queued", "prefill", "decode"):
        ph = by_name[name]
        assert ph["tid"] == req["tid"]
        assert ph["ts"] >= req["ts"]
        assert ph["ts"] + ph["dur"] <= req["ts"] + req["dur"] + 1.0
    # the training phase span sits on the dedicated track (tid 0),
    # its interval ENDING at the emit time (dur_ms measured wall)
    tr = by_name["round"]
    assert tr["tid"] == 0 and tr["cat"] == "train"
    assert tr["dur"] == pytest.approx(1.0e5)        # 100ms in us
    assert tr["ts"] + tr["dur"] == pytest.approx(2.0e6)
    assert tr["args"]["trace_id"] == tid and tr["args"]["step"] == 3
    # restart/anomaly instants: the span-stream one and the
    # restarts.jsonl one both land
    assert by_name["engine_restart"]["ph"] == "i"
    assert by_name["restart:engine_restart"]["ph"] == "i"
    # events are time-ordered with metadata first
    ts = [e.get("ts", -1.0) for e in events if e["ph"] != "M"]
    assert ts == sorted(ts)
    assert events[0]["ph"] == "M"


# --- fleet report (pure) ---------------------------------------------------


def test_fleet_report_exactly_once_and_identity(tmp_path):
    a = _write_rows(str(tmp_path / "a"),
                    _lifecycle(1.0, 0) + _lifecycle(1.5, 1))
    b = _write_rows(str(tmp_path / "b"), _lifecycle(1.2, 0))
    doc = col_lib.fleet_report([a, b])
    assert schema_lib.validate_fleet_report(doc) == []
    assert doc["exactly_once"] and doc["errors"] == []
    assert doc["requests"] == 3 and doc["restarts"] == 0
    assert [s["source"] for s in doc["sources"]] == ["a", "b"]
    assert doc["slo"]["kind"] == "fleet_slo_report"
    assert doc["slo"]["identity"]["holds"]
    assert doc["slo"]["sources"] == ["a", "b"]
    # a doctored duplicate terminal breaks the verdict, named by
    # SOURCE (the operator's handle), not the rewritten proc
    rows = _lifecycle(1.0, 0)
    rows.append(_row("retire", 9.9, rid=0, generated=2, finish_t=9.0,
                     tick=7))
    _write_rows(str(tmp_path / "a"),
                rows + _lifecycle(1.5, 1))
    doc = col_lib.fleet_report([a, b])
    assert not doc["exactly_once"]
    assert any(e.startswith("a rid 0:") and "duplicate retire" in e
               for e in doc["errors"])
    # an IN-FLIGHT request (no terminal yet) is not a violation
    c = _write_rows(str(tmp_path / "c"), [
        _row("submit", 1.0, rid=5, prompt_len=2, max_new_tokens=2,
             arrival=0.0)])
    doc = col_lib.fleet_report([c])
    assert doc["exactly_once"] and doc["requests"] == 1
    assert doc["slo"] is None               # no terminal records yet


def test_fleet_report_error_cap(tmp_path):
    """A corrupt fleet diagnoses, not floods: the errors list is
    capped at MAX_REPORT_ERRORS."""
    rows = []
    for rid in range(col_lib.MAX_REPORT_ERRORS + 20):
        rows += [_row("admit", 1.0 + rid, rid=rid, pages_held=1,
                      tick=0)]          # admit without submit: error
    a = _write_rows(str(tmp_path / "a"), rows)
    doc = col_lib.fleet_report([a])
    assert not doc["exactly_once"]
    assert len(doc["errors"]) == col_lib.MAX_REPORT_ERRORS


# --- the 3-engine chaos merge (CPU jax) ------------------------------------


def test_three_engine_fleet_merges_exactly_once_across_crash(tmp_path):
    """THE fleet acceptance case: three engines in three run dirs,
    one crashed mid-decode by a FaultPlan and supervised back up.
    The merged timeline reconstructs every accepted request
    fleet-wide to exactly one typed terminal, the crashed engine's
    requests keep their trace_id chain unbroken across the restart
    (requeue rides the SAME id the caller sent), and the federated
    SLO identity holds over the merge."""
    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.models import (
        transformer as tfm,
    )
    from distributed_tensorflow_example_tpu.resilience.restart import (
        RestartNarrator,
    )
    from distributed_tensorflow_example_tpu.serving.engine import (
        DecodeEngine,
    )
    from distributed_tensorflow_example_tpu.serving.faults import (
        FaultPlan,
    )

    spec = tfm.TransformerSpec(
        input_size=32, num_classes=10, seq_len=32, d_model=32,
        n_heads=2, num_blocks=2, d_ff=64, objective="lm",
        vocab_size=50, causal=True)
    params = tfm.init(jax.random.PRNGKey(0), spec)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 50, size=n).tolist()
               for n in (3, 6, 4, 5, 3, 7)]

    # the caller's trace for the crashed engine's first request: its
    # id must survive the requeue into the merged fleet record
    want_tid, want_parent = "fe" * 16, "aa" * 8
    hdr = spans_lib.format_traceparent(want_tid, want_parent)

    dirs, all_rids = [], {}
    for i in range(3):
        d = str(tmp_path / f"engine{i}")
        rec = spans_lib.SpanRecorder(d)
        kw = {}
        if i == 1:                      # the crashed member
            kw = dict(engine_retries=3,
                      faults=FaultPlan(crash_at_ticks=(1,)),
                      restart_narrator=RestartNarrator(d))
        eng = DecodeEngine(spec, params, page_size=4, max_batch=2,
                           recorder=rec, **kw)
        rids = [eng.submit(prompts[2 * i + j], 4,
                           traceparent=hdr if (i, j) == (1, 0)
                           else None)
                for j in range(2)]
        assert eng.trace_context(rids[0]) is not None
        eng.run_until_idle()
        results = [eng.result(r, timeout=60.0) for r in rids]
        assert [r["status"] for r in results] == ["result"] * 2
        rec.close()
        dirs.append(d)
        all_rids[f"engine{i}"] = rids

    doc = col_lib.fleet_report(dirs)
    assert schema_lib.validate_fleet_report(doc) == []
    # fleet-wide exactly-once: 6 requests, every one a single typed
    # terminal, no reconstruction errors — across the crash
    assert doc["exactly_once"], doc["errors"]
    assert doc["requests"] == 6
    assert doc["restarts"] >= 1             # the FaultPlan crash
    assert [s["source"] for s in doc["sources"]] == [
        "engine0", "engine1", "engine2"]
    # the merged reconstruction: typed result terminals everywhere,
    # and every request carries SOME stable trace_id
    col = col_lib.collect(dirs)
    recs = spans_lib.reconstruct(
        [r for r in col["rows"] if r.get("kind") == "span"])
    assert len(recs) == 6
    for key, r in recs.items():
        assert r["terminal"] == "result" and r["complete"], \
            (key, r["errors"])
        assert len(r.get("trace_id") or "") == 32, key
    # the caller-traced request on the crashed engine: id + parent
    # exactly as sent, with the restart visibly on its record
    by_src = {(r["source"], r["rid"]): r for r in recs.values()}
    traced = by_src[("engine1", all_rids["engine1"][0])]
    assert traced["trace_id"] == want_tid
    assert traced["parent_id"] == want_parent
    # the federated SLO identity holds over the merged stream
    assert doc["slo"]["identity"]["holds"]
    assert doc["slo"]["sources"] == ["engine0", "engine1", "engine2"]
