"""Multi-process smoke test (SURVEY.md §4: "multi-process init is
covered with jax.distributed over localhost subprocesses").

Launches the real CLI in two OS processes on the CPU backend, sharing a
localhost coordinator — the analog of the reference's 4-host run
(README.md:11-16, the only way the reference was ever 'tested'). Covers
jax.distributed bootstrap from the reference flags, the
make_array_from_process_local_data batch assembly in the host loop, and
chief-only final prints. (Larger topologies, cross-process TP, and
kill/resume live in test_multiprocess_scale.py.)
"""

from mp_utils import run_all


def test_two_process_localhost_training():
    outs = run_all(2, 2, [
        "--training_epochs=1", "--batch_size=64", "--frequency=5",
        "--synthetic_train_size=1024", "--synthetic_test_size=256",
    ])
    chief_out, worker_out = outs
    # chief prints the final block (example.py:177-182); non-chief doesn't
    assert "Test-Accuracy:" in chief_out and "done" in chief_out, chief_out[-2000:]
    assert "Test-Accuracy:" not in worker_out
    # both processes train: step lines present, and the data is sharded —
    # each process sees (1024/2)/32 = 16 batches per epoch
    assert "Batch:  16 of  16," in chief_out, chief_out[-2000:]
    assert "Batch:  16 of  16," in worker_out


def test_eval_all_hosts_prints_everywhere():
    """--eval_all_hosts mirrors the reference's per-worker final eval
    (example.py:177: every worker prints Test-Accuracy)."""
    outs = run_all(2, 2, [
        "--training_epochs=1", "--batch_size=64", "--frequency=5",
        "--synthetic_train_size=512", "--synthetic_test_size=128",
        "--eval_all_hosts",
    ])
    chief_out, worker_out = outs
    assert "Test-Accuracy:" in chief_out, chief_out[-2000:]
    assert "Test-Accuracy:" in worker_out, worker_out[-2000:]
    # the rest of the final block stays chief-only
    assert "Total Time:" in chief_out and "Total Time:" not in worker_out
