"""Multi-process smoke test (SURVEY.md §4: "multi-process init is
covered with jax.distributed over localhost subprocesses").

Launches the real CLI in two OS processes on the CPU backend, sharing a
localhost coordinator — the analog of the reference's 4-host run
(README.md:11-16, the only way the reference was ever 'tested'). Covers
jax.distributed bootstrap from the reference flags, the
make_array_from_process_local_data batch assembly in the host loop, and
chief-only final prints.
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_localhost_training():
    port = _free_port()
    env = dict(os.environ)
    env["DTX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    ).strip()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def launch(task_index: int):
        return subprocess.Popen(
            [
                sys.executable, "-m", "distributed_tensorflow_example_tpu.main",
                "--job_name=worker", f"--task_index={task_index}",
                f"--coordinator_address=127.0.0.1:{port}",
                "--num_processes=2",
                "--training_epochs=1", "--batch_size=64", "--frequency=5",
                "--dataset=synthetic", "--synthetic_train_size=1024",
                "--synthetic_test_size=256", "--no_summaries",
                "--compilation_cache=",
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    procs = [launch(0), launch(1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]

    chief_out, worker_out = outs
    # chief prints the final block (example.py:177-182); non-chief doesn't
    assert "Test-Accuracy:" in chief_out and "done" in chief_out, chief_out[-2000:]
    assert "Test-Accuracy:" not in worker_out
    # both processes train: step lines present, and the data is sharded —
    # each process sees (1024/2)/32 = 16 batches per epoch
    assert "Batch:  16 of  16," in chief_out, chief_out[-2000:]
    assert "Batch:  16 of  16," in worker_out
