"""Golden stdout-format test (SURVEY.md §4: byte-for-byte modulo values
vs example.py:169-179) plus a short end-to-end integration run."""

import io
import re
import contextlib

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.data import mnist as M
from distributed_tensorflow_example_tpu.train.loop import run

STEP_RE = re.compile(
    r"^Step: \d+,  Epoch: [ \d]\d,  Batch: [ \d]{3} of [ \d]{3},"
    r"  Cost: \d+\.\d{4},  AvgTime: +\d+\.\d{2}ms$"
)


@pytest.fixture(scope="module")
def small_dataset(monkeypatch=None):
    """Shrink the synthetic dataset so the run is fast on 1 CPU core."""
    return M.Dataset(
        train=M.synthesize_split(2000, seed=1),
        validation=M.synthesize_split(200, seed=2),
        test=M.synthesize_split(500, seed=3),
        source="synthetic",
    )


def _run_captured(cfg, small_dataset, monkeypatch):
    import distributed_tensorflow_example_tpu.train.loop as loop_mod

    monkeypatch.setattr(loop_mod, "load_datasets", lambda *a, **k: small_dataset)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        res = run(cfg)
    return buf.getvalue(), res


def test_stdout_format_matches_reference(small_dataset, monkeypatch, tmp_path):
    cfg = Config(training_epochs=1, frequency=5, summaries=True,
                 logs_path=str(tmp_path), data_parallel=1)
    out, res = _run_captured(cfg, small_dataset, monkeypatch)
    lines = out.strip().split("\n")
    assert lines[0] == "Variables initialized ..."          # example.py:130
    step_lines = [l for l in lines if l.startswith("Step:")]
    assert len(step_lines) >= 4
    for l in step_lines:
        assert STEP_RE.match(l), repr(l)
    # final block, example.py:177-179, 182
    assert re.match(r"^Test-Accuracy: \d+\.\d{2}$", lines[-4])
    assert re.match(r"^Total Time: \d+\.\d{2}s$", lines[-3])
    assert re.match(r"^Final Cost: \d+\.\d{4}$", lines[-2])
    assert lines[-1] == "done"


def test_summaries_written_per_step(small_dataset, monkeypatch, tmp_path):
    import glob, os

    cfg = Config(training_epochs=1, summaries=True, logs_path=str(tmp_path),
                 data_parallel=1)
    _, res = _run_captured(cfg, small_dataset, monkeypatch)
    from distributed_tensorflow_example_tpu.utils.summary import read_event_file

    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    assert len(files) == 1
    events = read_event_file(files[0])
    scalar_events = [e for e in events if e["scalars"]]
    # the reference writes cost+accuracy every step (example.py:163)
    assert len(scalar_events) == res["steps"]
    assert set(scalar_events[0]["scalars"]) == {"cost", "accuracy"}


def test_short_training_learns(small_dataset, monkeypatch):
    """Integration (SURVEY.md §4): accuracy far above chance after a
    short adam/relu run on 8 devices."""
    cfg = Config(training_epochs=8, optimizer="adam", learning_rate=0.005,
                 hidden_sizes=(64,), activation="relu", batch_size=96,
                 data_parallel=8, summaries=False)
    _, res = _run_captured(cfg, small_dataset, monkeypatch)
    assert res["test_accuracy"] > 0.5, res
    assert res["dataset_source"] == "synthetic"


def test_resume_roundtrip(small_dataset, monkeypatch, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = Config(training_epochs=1, summaries=False, checkpoint_dir=ckpt_dir,
                 data_parallel=1)
    _, res1 = _run_captured(cfg, small_dataset, monkeypatch)
    cfg2 = cfg.replace(training_epochs=2, resume=True)
    out, res2 = _run_captured(cfg2, small_dataset, monkeypatch)
    # resumed at epoch 1: only one more epoch of steps
    assert res2["steps"] == res1["steps"] * 2
    assert "Resumed from" in out


def test_checkpoint_every_boundary_crossing(small_dataset, monkeypatch, tmp_path):
    """Periodic checkpoints fire when a checkpoint_every boundary is
    crossed, even when it doesn't divide the epoch length (2000-example
    dataset, batch 100 -> 20-step epochs; every=30 -> saves after epochs
    2, 4, 6 at steps 40, 80, 120... boundary-crossing rule)."""
    import glob, os

    ckpt_dir = str(tmp_path / "ck")
    cfg = Config(training_epochs=4, summaries=False, data_parallel=1,
                 checkpoint_dir=ckpt_dir, checkpoint_every=30)
    _, res = _run_captured(cfg, small_dataset, monkeypatch)
    names = sorted(os.path.basename(p) for p in glob.glob(ckpt_dir + "/ckpt-*.npz"))
    # epochs end at steps 20,40,60,80; boundary 30 crossed at 40 (1x) and
    # 60 (2x... 60//30=2 > 40//30=1) and 80 is 2 -> not; plus final save at 80
    assert "ckpt-00000040.npz" in names and "ckpt-00000060.npz" in names, names


def test_resume_does_not_retrain_completed_epoch(small_dataset, monkeypatch, tmp_path):
    """A checkpoint after a completed epoch resumes at the NEXT epoch."""
    import numpy as np
    from distributed_tensorflow_example_tpu.utils import checkpoint as C

    ckpt_dir = str(tmp_path / "ck")
    cfg = Config(training_epochs=2, summaries=False, data_parallel=1,
                 checkpoint_dir=ckpt_dir, checkpoint_every=20)
    _run_captured(cfg, small_dataset, monkeypatch)
    path = C.latest_checkpoint(ckpt_dir)
    with np.load(path) as z:
        step, epoch = int(z["__step__"]), int(z["__epoch__"])
    assert step == 40 and epoch == 2  # final save: all epochs done
