"""Rolling bench history + the --gate-rolling baseline (ISSUE 12).

Pure file I/O over strict JSON — every test runs in this container.
The committed BENCH_r0*.json captures double as fixtures: the
--import backfill is exercised against the real artifacts the
trajectory is supposed to start from.
"""

import json
import os

import pytest

from distributed_tensorflow_example_tpu.obs import cli as cli_lib
from distributed_tensorflow_example_tpu.obs import compare as cmp_lib
from distributed_tensorflow_example_tpu.obs import history as hist_lib
from distributed_tensorflow_example_tpu.obs import schema as schema_lib

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CAPTURES = [os.path.join(_REPO, f"BENCH_r0{i}.json")
             for i in range(1, 6)]


def _summary(wall, mfu=0.5, acc=0.9):
    return {"metric": "mnist_20epoch_wall_clock", "value": wall,
            "mfu": mfu, "learning_accuracy": acc}


# --- append / read / schema ------------------------------------------------


def test_append_read_round_trip(tmp_path):
    path = str(tmp_path / "history.jsonl")
    e1 = hist_lib.append_entry(path, _summary(10.0), label="r1",
                               source="bench")
    assert e1["metrics"] == {"wall_s": 10.0, "mfu": 0.5,
                             "test_accuracy": 0.9}
    hist_lib.append_entry(path, _summary(11.0), label="r2",
                          source="bench")
    entries = hist_lib.read_history(path)
    assert [e["label"] for e in entries] == ["r1", "r2"]
    assert entries[0]["v"] == schema_lib.SCHEMA_VERSION
    assert hist_lib.validate_file(path) == []
    assert schema_lib.validate_history_file(path) == []
    # every line is strict JSON
    for line in open(path):
        json.dumps(json.loads(line), allow_nan=False)
    # a run report is an accepted input shape too (extract_metrics)
    rep = {"v": schema_lib.SCHEMA_VERSION, "kind": "run_report",
           "wall_s": 5.0, "test_accuracy": 0.8,
           "goodput": {"goodput_frac": 0.7}, "step_time": {},
           "throughput": {}}
    e = hist_lib.append_entry(path, rep, label="report")
    assert e["metrics"]["wall_s"] == 5.0
    assert e["metrics"]["goodput_frac"] == 0.7


def test_read_history_survives_torn_and_foreign_lines(tmp_path):
    path = str(tmp_path / "history.jsonl")
    hist_lib.append_entry(path, _summary(1.0), label="ok")
    with open(path, "a") as f:
        f.write("{torn\n")
        f.write(json.dumps({"kind": "window", "v": 4}) + "\n")
    entries = hist_lib.read_history(path)
    assert [e["label"] for e in entries] == ["ok"]
    # the strict validator DOES flag those lines
    assert hist_lib.validate_file(path) != []
    assert hist_lib.read_history(str(tmp_path / "missing.jsonl")) == []


def test_validate_history_entry_contract(tmp_path):
    path = str(tmp_path / "history.jsonl")
    e = hist_lib.append_entry(path, _summary(1.0), label="x")
    assert schema_lib.validate_history_entry(e) == []
    errs = schema_lib.validate_history_entry(
        {k: v for k, v in e.items() if k != "metrics"})
    assert errs and "metrics" in errs[0]
    errs = schema_lib.validate_history_entry(
        {k: v for k, v in e.items() if k != "v"})
    assert len(errs) == 1 and "schema v1" in errs[0]


# --- rolling baseline ------------------------------------------------------


def test_rolling_baseline_median_closed_form(tmp_path):
    path = str(tmp_path / "h.jsonl")
    for i, wall in enumerate((10.0, 20.0, 30.0, 40.0, 50.0)):
        hist_lib.append_entry(path, _summary(wall, mfu=0.1 * (i + 1)),
                              label=f"r{i}")
    entries = hist_lib.read_history(path)
    base = hist_lib.rolling_baseline(entries, 3)       # last 3
    assert base["kind"] == "history_baseline"
    assert base["entries"] == 3
    assert base["metrics"]["wall_s"] == 40.0           # median(30,40,50)
    assert base["metrics"]["mfu"] == pytest.approx(0.4)
    # a metric present in only SOME entries still contributes
    hist_lib.append_entry(path, {"metric": "x", "value": 60.0,
                                 "serving_p99_ms": 100.0}, label="r5")
    base = hist_lib.rolling_baseline(hist_lib.read_history(path), 2)
    assert base["metrics"]["serving_p99_ms"] == 100.0
    assert base["metrics"]["wall_s"] == 55.0           # median(50,60)


def test_history_shapes_flow_through_compare():
    """The bench_history/history_baseline shapes are first-class
    compare documents — including metrics (prefetch_step_ms) whose
    names would hijack other extract_metrics branches if the dict
    were fed in bare."""
    base = {"kind": "history_baseline", "entries": 3,
            "metrics": {"wall_s": 10.0, "prefetch_step_ms": 9.0,
                        "mfu": 0.5, "bogus_metric": 1.0,
                        "test_accuracy": "doctored"}}
    m = cmp_lib.extract_metrics(base)
    # every gate metric survives side by side; non-gate and
    # non-numeric entries are filtered
    assert m == {"wall_s": 10.0, "prefetch_step_ms": 9.0, "mfu": 0.5}
    entry = {"kind": "bench_history", "label": "r1",
             "metrics": {"wall_s": 12.0}}
    assert cmp_lib.extract_metrics(entry) == {"wall_s": 12.0}
    # the rolling gate verdict: a doctored 50% wall regression gates
    verdict = cmp_lib.compare(base, _summary(15.0))
    assert not verdict["ok"] and "wall_s" in verdict["regressions"]
    assert cmp_lib.compare(base, _summary(10.0))["ok"]


# --- the --import backfill over the committed captures ---------------------


def test_import_committed_captures_idempotent(tmp_path):
    path = str(tmp_path / "history.jsonl")
    appended, skipped = hist_lib.import_captures(path, _CAPTURES)
    assert appended == 5 and skipped == []
    entries = hist_lib.read_history(path)
    assert [e["label"] for e in entries] == [
        f"BENCH_r0{i}" for i in range(1, 6)]
    assert all(e["source"] == "import" for e in entries)
    # every committed capture yields at least one gate metric — the
    # trajectory starts non-empty (the acceptance criterion)
    assert all(e["metrics"] for e in entries)
    assert hist_lib.validate_file(path) == []
    base = hist_lib.rolling_baseline(entries, 5)
    assert "wall_s" in base["metrics"]
    # re-import: nothing duplicated
    appended, skipped = hist_lib.import_captures(path, _CAPTURES)
    assert appended == 0 and len(skipped) == 5
    assert len(hist_lib.read_history(path)) == 5
    # unreadable captures are reported, not fatal
    appended, skipped = hist_lib.import_captures(
        path, [str(tmp_path / "ghost.json")])
    assert appended == 0 and "unreadable" in skipped[0]


# --- trend table + CLI -----------------------------------------------------


def test_trend_table(tmp_path):
    path = str(tmp_path / "h.jsonl")
    hist_lib.append_entry(path, _summary(10.0), label="r1")
    hist_lib.append_entry(path, _summary(12.0, mfu=0.6), label="r2")
    table = hist_lib.trend_table(hist_lib.read_history(path))
    lines = table.splitlines()
    assert lines[0].startswith("label")
    assert "wall_s" in lines[0] and "mfu" in lines[0]
    assert lines[1].startswith("r1") and "10" in lines[1]
    assert lines[2].startswith("r2") and "0.6" in lines[2]
    # column selection + last-N
    table = hist_lib.trend_table(hist_lib.read_history(path),
                                 metrics=["wall_s"], last=1)
    assert "mfu" not in table and "r1" not in table


def test_cli_history(tmp_path, capsys):
    path = str(tmp_path / "history.jsonl")
    assert cli_lib.main(["history", path]) == 2        # empty
    capsys.readouterr()
    assert cli_lib.main(["history", path, "--import"] + _CAPTURES) == 0
    cap = capsys.readouterr()
    assert "imported 5" in cap.err
    assert "BENCH_r01" in cap.out                      # trend table
    assert cli_lib.main(["history", path, "--json"]) == 0
    entries = json.loads(capsys.readouterr().out)
    assert len(entries) == 5
    # --append records any comparison doc (here: a saved summary)
    doc = tmp_path / "run.json"
    doc.write_text(json.dumps(_summary(9.0)))
    assert cli_lib.main(["history", path, "--append", str(doc)]) == 0
    capsys.readouterr()
    assert len(hist_lib.read_history(path)) == 6
    assert cli_lib.main(["history", path, "--append",
                         str(tmp_path / "nope.json")]) == 2
    capsys.readouterr()
    # validate routes history files by kind (arbitrary basename)
    assert cli_lib.main(["validate", path]) == 0
    out = capsys.readouterr().out
    assert out.startswith("OK")


def test_cli_validate_routes_past_torn_first_line(tmp_path, capsys):
    """The kind-peek scans to the first WELL-FORMED row: a torn first
    line (crashed writer) must not misroute a history file to the
    metrics validator (which would flag every valid record)."""
    path = str(tmp_path / "h.jsonl")
    with open(path, "w") as f:
        f.write("{torn\n")
    hist_lib.append_entry(path, _summary(1.0), label="ok")
    assert cli_lib.main(["validate", path]) == 1   # the torn line only
    out = capsys.readouterr().out
    assert "not JSON" in out
    assert "bench_history" not in out   # no kind-mismatch cascade
