"""Latency attribution (ISSUE 17): waterfalls, queueing, drift.

Three halves, mirroring the layer's own split:

- **pure decomposition** (no jax): the waterfall state machine on
  synthetic span rows — disjoint segments tiling submit→terminal
  EXACTLY (residual 0 by construction), brownout/requeue labeling,
  the closed-form Little's-law identity (exact when every arrival
  terminates in-window, violations counting the in-flight gap), and
  the change-point golden (a doctored history names the metric and
  the FIRST offending row; a clean one stays quiet);
- **CLI + server surfaces**: ``dtx-obs explain``/``drift`` exit
  codes, the ``/explain`` endpoint + ``dtx_waterfall_*`` gauges, the
  shared TTLCache discipline, and the ``--status_cache_s`` flag's
  validation;
- **engine chaos property suite** (CPU jax): the REAL DecodeEngine
  under a FaultPlan crash + requeue + shed + timeout workload — for
  EVERY request the derived segments are non-negative, the intervals
  are disjoint and tile the wall, and the residual is ≤ 1% of wall
  (the ``bench_latency_attribution`` gate, proven per-rid here).
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.obs import buckets as bk
from distributed_tensorflow_example_tpu.obs import cli as cli_lib
from distributed_tensorflow_example_tpu.obs import drift as drift_lib
from distributed_tensorflow_example_tpu.obs import (
    queueing as queueing_lib,
)
from distributed_tensorflow_example_tpu.obs import schema as schema_lib
from distributed_tensorflow_example_tpu.obs import serve as serve_lib
from distributed_tensorflow_example_tpu.obs import spans as spans_lib
from distributed_tensorflow_example_tpu.obs import (
    waterfall as wf_lib,
)

V = schema_lib.SCHEMA_VERSION


def _row(event, t, rid=None, proc=0, **kw):
    r = {"kind": "span", "v": V, "event": event, "t": t, "proc": proc}
    if rid is not None:
        r["rid"] = rid
    r.update(kw)
    return r


def _assert_tiles(doc):
    """THE invariant: the intervals are sorted, disjoint, and tile
    [submit_t, terminal_t] exactly — so the segments must sum to the
    wall with zero residual."""
    iv = doc["intervals"]
    if not iv:
        assert doc["wall_ms"] == 0.0
        return
    assert iv[0][0] == doc["submit_t"]
    assert abs(iv[-1][1] - doc["terminal_t"]) < 1e-9
    for (a0, a1, _s), (b0, _b1, _s2) in zip(iv, iv[1:]):
        assert a1 <= b0 + 1e-12 and a0 < a1
        assert abs(a1 - b0) < 1e-9          # no gap either
    assert abs(doc["residual_ms"]) <= max(doc["wall_ms"] * 0.01, 1e-3)


# --- the state machine on synthetic rows ---------------------------------


def test_waterfall_simple_lifecycle_partitions_exactly():
    rows = [
        _row("submit", 0.0, rid=0),
        _row("blocked", 0.2, rid=0, reason="slots"),
        _row("admit", 1.0, rid=0),
        _row("tick", 1.0, tick=0, rids=[0]),
        _row("first_token", 2.0, rid=0),
        _row("tick", 2.0, tick=1, rids=[0]),
        _row("tick_done", 2.5, tick=1, dur_ms=300.0),
        _row("retire", 3.0, rid=0),
    ]
    docs = wf_lib.waterfalls(rows)
    assert len(docs) == 1
    d = docs[0]
    assert d["terminal"] == "result" and d["complete"]
    assert d["wall_ms"] == pytest.approx(3000.0)
    segs = d["segments"]
    # slot-blocked waiting IS queue_wait; admit→first_token is
    # prefill; the tick_done pair splits decode into the execution
    # window [2.2, 2.5] and the trailing gap, re-labeled finalize
    # because the retire narration lands at the next boundary
    assert segs["queue_wait"] == pytest.approx(1000.0)
    assert segs["prefill"] == pytest.approx(1000.0)
    assert segs["decode_active"] == pytest.approx(500.0)
    assert segs["finalize"] == pytest.approx(500.0)
    assert segs["decode_stall"] == 0.0 and segs["requeue"] == 0.0
    assert d["residual_ms"] == pytest.approx(0.0, abs=1e-6)
    _assert_tiles(d)
    assert schema_lib.validate_waterfall(d) == []


def test_waterfall_brownout_and_requeue_are_attributed():
    rows = [
        _row("submit", 0.0, rid=7),
        _row("blocked", 0.5, rid=7, reason="brownout"),
        _row("admit", 1.5, rid=7),
        _row("first_token", 2.0, rid=7),
        _row("requeue", 2.5, rid=7),         # supervised restart
        # post-restart blocked waiting is restart overhead, NOT
        # ordinary queueing — the state must stay "requeue"
        _row("blocked", 2.7, rid=7, reason="slots"),
        _row("admit", 3.0, rid=7),
        _row("first_token", 3.5, rid=7),
        _row("retire", 4.0, rid=7),
    ]
    d = wf_lib.waterfalls(rows)[0]
    segs = d["segments"]
    assert segs["queue_wait"] == pytest.approx(500.0)
    assert segs["brownout_clamp_delay"] == pytest.approx(1000.0)
    assert segs["requeue"] == pytest.approx(500.0)
    assert d["requeues"] == 1
    assert d["residual_ms"] == pytest.approx(0.0, abs=1e-6)
    _assert_tiles(d)


def test_waterfall_without_tick_done_degrades_to_decode_active():
    """Older streams (schema < v8, the pure tick simulator) carry no
    tick_done close: decode time must stay decode_active, never be
    invented as stall."""
    rows = [
        _row("submit", 0.0, rid=0),
        _row("admit", 0.1, rid=0),
        _row("first_token", 0.2, rid=0),
        _row("tick", 0.2, tick=0, rids=[0]),
        _row("tick", 0.4, tick=1, rids=[0]),
        _row("retire", 0.6, rid=0),
    ]
    d = wf_lib.waterfalls(rows)[0]
    assert d["segments"]["decode_active"] == pytest.approx(400.0)
    assert d["segments"]["decode_stall"] == 0.0
    assert d["residual_ms"] == pytest.approx(0.0, abs=1e-6)


def test_waterfall_filters_and_incomplete():
    rows = [
        _row("submit", 0.0, rid=0, trace_id="a" * 32),
        _row("retire", 1.0, rid=0),
        _row("submit", 0.5, rid=1),          # no terminal: in flight
    ]
    assert len(wf_lib.waterfalls(rows)) == 2
    assert [d["rid"] for d in wf_lib.waterfalls(rows, rid=1)] == [1]
    by_trace = wf_lib.waterfalls(rows, trace_id="a" * 32)
    assert [d["rid"] for d in by_trace] == [0]
    d1 = wf_lib.waterfalls(rows, rid=1)[0]
    assert not d1["complete"] and d1["terminal"] is None
    summ = wf_lib.summarize(wf_lib.waterfalls(rows))
    assert summ["requests"] == 2 and summ["complete"] == 1
    assert summ["terminals"] == {"result": 1}
    assert summ["sum_to_wall_ok"]


def test_waterfall_segment_registry_is_closed():
    """Every label the state machine can produce is registered (the
    scope-registry discipline), and the schema validator rejects an
    unknown segment."""
    assert set(bk.WATERFALL_SEGMENTS) >= {
        "queue_wait", "brownout_clamp_delay", "prefill",
        "decode_active", "decode_stall", "requeue", "finalize"}
    rows = [_row("submit", 0.0, rid=0), _row("retire", 1.0, rid=0)]
    d = wf_lib.waterfalls(rows)[0]
    d["segments"]["made_up"] = 1.0
    assert any("made_up" in e for e in schema_lib.validate_waterfall(d))


def test_tick_done_emission_validates():
    """The recorder accepts the v8 tick_done row and the span-file
    validator passes the pair."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        rec = spans_lib.SpanRecorder(tmp)
        rec.emit("tick", tick=0, rids=[0], batch=1,
                 batch_bucket=1, kv_pages=2, occupancy=0.5)
        rec.emit("tick_done", tick=0, dur_ms=1.25)
        rec.close()
        assert schema_lib.validate_span_file(rec.path) == []
        rows = spans_lib.read_spans(rec.path)
        done = [r for r in rows if r["event"] == "tick_done"]
        assert len(done) == 1 and done[0]["dur_ms"] == 1.25


# --- queueing analytics: the Little's-law identity -----------------------


def _lifecycle(rid, submit, admit, retire, bucket=4, proc=0):
    return [
        _row("submit", submit, rid=rid, proc=proc),
        _row("admit", admit, rid=rid, proc=proc),
        _row("prefill", admit, rid=rid, proc=proc, bucket=bucket),
        _row("retire", retire, rid=rid, proc=proc),
    ]


def test_littles_law_identity_exact_closed_form():
    """3 requests, sojourn 2 s each over a 4 s window:
    L = (2+2+2)/4 = 1.5 and λ·W = (3/4)·2 = 1.5 — the identity is
    EXACT when every arrival terminates in-window."""
    rows = (_lifecycle(0, 0.0, 0.5, 2.0)
            + _lifecycle(1, 1.0, 1.5, 3.0)
            + _lifecycle(2, 2.0, 2.5, 4.0))
    rep = queueing_lib.queueing_report(rows)
    ll = rep["littles_law"]
    assert rep["arrivals"] == 3 and rep["completed"] == 3
    assert ll["L"] == pytest.approx(1.5)
    assert ll["lambda_W"] == pytest.approx(1.5)
    assert ll["rel_err"] == pytest.approx(0.0, abs=1e-9)
    assert ll["holds"] and ll["violations"] == 0
    assert rep["arrival_rate_per_s"] == pytest.approx(0.75)
    # per-bucket service time: admit → terminal
    assert rep["service_ms_by_bucket"]["4"]["n"] == 3
    assert rep["service_ms_by_bucket"]["4"]["mean_ms"] == (
        pytest.approx(1500.0))


def test_littles_law_flags_untracked_time():
    """A request with no terminal (torn tail, crashed writer) is the
    violation that explains the identity gap."""
    rows = (_lifecycle(0, 0.0, 0.5, 2.0)
            + _lifecycle(1, 1.0, 1.5, 3.0)[:3])   # no retire
    rep = queueing_lib.queueing_report(rows)
    ll = rep["littles_law"]
    assert rep["in_flight"] == 1 and ll["violations"] == 1
    assert ll["rel_err"] > 0.05 and not ll["holds"]


def test_queueing_report_empty_is_none():
    assert queueing_lib.queueing_report([]) is None
    assert queueing_lib.queueing_report(
        [_row("tick", 0.0, tick=0, rids=[])]) is None


# --- drift detection: the change-point golden ----------------------------


def _hist(path, values, metric="decode_step_ms"):
    with open(path, "w") as f:
        for i, v in enumerate(values):
            f.write(json.dumps({
                "v": V, "kind": "bench_history", "t": 1000.0 + i,
                "label": f"r{i}", "source": f"BENCH_r{i}.json",
                "metrics": {metric: v, "wall_s": 10.0},
            }) + "\n")
    return str(path)


def test_detect_names_first_offending_row():
    vals = [10.0, 10.2, 9.9, 10.1, 10.0, 13.0, 13.1, 12.9, 13.2, 13.0]
    # a gated "lower"-is-better metric drifts UP
    d = drift_lib.detect([f"r{i}" for i in range(10)], vals,
                         "step_time_p50_ms")
    assert d is not None
    assert d["metric"] == "step_time_p50_ms"
    assert d["direction"] == "lower"
    assert d["first_offending"] == "r5"
    assert d["first_offending_index"] == 5
    assert d["shift_frac"] > 0.25
    # an IMPROVEMENT (downward shift) is not a drift for it
    assert drift_lib.detect(
        [f"r{i}" for i in range(10)], vals[::-1],
        "step_time_p50_ms") is None
    # an ungated metric drifts either way (direction "any")
    d = drift_lib.detect([f"r{i}" for i in range(10)], vals[::-1],
                         "decode_step_ms")
    assert d is not None and d["direction"] == "any"
    # one noisy spike is NOT a level shift — medians absorb it
    spike = [10.0, 10.2, 9.9, 13.0, 10.1, 10.0, 9.8, 10.2]
    assert drift_lib.detect([f"r{i}" for i in range(8)], spike,
                            "step_time_p50_ms") is None


def test_drift_cli_exit_codes(tmp_path, capsys):
    flat = [10.0, 10.2, 9.9, 10.1, 10.0, 9.8]
    doctored = flat[:4] + [13.0, 13.1, 12.9, 13.2]
    clean = _hist(tmp_path / "clean.jsonl", flat)
    bad = _hist(tmp_path / "bad.jsonl", doctored)

    assert cli_lib.main(["drift", clean]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] and doc["drifts"] == []
    assert schema_lib.validate_drift_report(doc) == []

    assert cli_lib.main(["drift", bad]) == 3
    out = capsys.readouterr()
    doc = json.loads(out.out)
    assert not doc["ok"]
    names = [d["metric"] for d in doc["drifts"]]
    assert "decode_step_ms" in names
    d = next(x for x in doc["drifts"] if x["metric"] == "decode_step_ms")
    assert d["first_offending"] == "r4"
    assert "decode_step_ms" in out.err and "r4" in out.err

    # too-short history and a missing file are usage errors, never a
    # fabricated verdict
    short = _hist(tmp_path / "short.jsonl", [10.0, 10.1])
    assert cli_lib.main(["drift", short]) == 2
    capsys.readouterr()
    assert cli_lib.main(["drift", str(tmp_path / "ghost.jsonl")]) == 2
    capsys.readouterr()
    # --metrics restricts the scan; wall_s alone stays clean
    assert cli_lib.main(["drift", bad, "--metrics", "wall_s"]) == 0
    capsys.readouterr()


# --- CLI explain + tail filters ------------------------------------------


def _span_file(tmp_path, rows):
    p = tmp_path / "spans.0.jsonl"
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(tmp_path)


def _two_request_rows():
    return (
        [_row("submit", 0.0, rid=0, trace_id="a" * 32),
         _row("admit", 0.4, rid=0),
         _row("first_token", 0.8, rid=0),
         _row("retire", 1.0, rid=0)]
        + [_row("submit", 0.2, rid=1),
           _row("admit", 0.6, rid=1),
           _row("first_token", 0.9, rid=1),
           _row("retire", 1.4, rid=1)]
    )


def test_cli_explain(tmp_path, capsys):
    d = _span_file(tmp_path, _two_request_rows())
    assert cli_lib.main(["explain", d]) == 0
    out = capsys.readouterr().out
    assert "rid 0" in out and "rid 1" in out
    assert "sum-to-wall OK" in out
    assert cli_lib.main(["explain", d, "--rid", "1", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert [w["rid"] for w in doc["waterfalls"]] == [1]
    assert doc["summary"]["sum_to_wall_ok"]
    assert cli_lib.main(["explain", d, "--trace", "a" * 32,
                         "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert [w["rid"] for w in doc["waterfalls"]] == [0]
    assert cli_lib.main(["explain", d, "--fleet"]) == 0
    fleet = json.loads(capsys.readouterr().out)
    assert fleet["littles_law"]["holds"]
    # no such rid / no stream at all: exit 2, not an empty success
    assert cli_lib.main(["explain", d, "--rid", "99"]) == 2
    capsys.readouterr()
    assert cli_lib.main(["explain", str(tmp_path / "nope")]) == 2
    capsys.readouterr()


def test_cli_tail_rid_and_trace_filters(tmp_path, capsys):
    rows = _two_request_rows() + [
        _row("tick", 0.85, tick=0, rids=[0, 1], occupancy=1.0),
        _row("tick_done", 0.95, tick=0, dur_ms=100.0),
    ]
    d = _span_file(tmp_path, rows)
    assert cli_lib.main(["tail", d, "--rid", "0"]) == 0
    out = capsys.readouterr().out
    assert "rid 0" in out and "rid 1" not in out
    # member tick rows (rids carries the rid) ride along
    assert "tick" in out
    assert cli_lib.main(["tail", d, "--trace", "a" * 32]) == 0
    out = capsys.readouterr().out
    assert "rid 0" in out and "rid 1" not in out
    # unfiltered: the tick_done row formats with its duration
    assert cli_lib.main(["tail", d]) == 0
    out = capsys.readouterr().out
    assert "tick_done" in out and "100" in out


# --- the status server: /explain + the shared TTL cache ------------------


def test_ttl_cache_semantics():
    calls = []

    def compute():
        calls.append(1)
        return len(calls)

    c = serve_lib.TTLCache(ttl_s=3600.0)
    assert c.get(compute) == 1
    assert c.get(compute) == 1          # cached within TTL
    assert len(calls) == 1
    # a signature change invalidates even inside the TTL
    assert c.get(compute, sig="a") == 2
    assert c.get(compute, sig="a") == 2
    assert c.get(compute, sig="b") == 3
    # ttl 0 recomputes every time (--status_cache_s 0)
    z = serve_lib.TTLCache(ttl_s=0.0)
    assert z.get(compute) == 4 and z.get(compute) == 5
    # None is a legitimate cached value, not a miss
    n = serve_lib.TTLCache(ttl_s=3600.0)
    assert n.get(lambda: calls.append(1) or None) is None
    before = len(calls)
    assert n.get(lambda: calls.append(1) or None) is None
    assert len(calls) == before


def test_explain_endpoint_and_waterfall_gauges(tmp_path):
    _span_file(tmp_path, _two_request_rows())
    srv = serve_lib.StatusServer(str(tmp_path), cache_ttl_s=0.0)
    port = srv.start(0)
    assert port
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return r.status, r.read().decode()

        code, body = get("/explain")
        assert code == 200
        doc = json.loads(body)
        assert doc["summary"]["requests"] == 2
        assert doc["summary"]["sum_to_wall_ok"]
        code, body = get("/explain?rid=1")
        assert [w["rid"] for w in json.loads(body)["waterfalls"]] == [1]
        # a malformed rid is a 400, not a traceback
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/explain?rid=zzz")
        assert ei.value.code == 400
        code, body = get("/metrics")
        assert "dtx_waterfall_requests 2" in body
        assert "dtx_waterfall_residual_frac_max" in body
        assert 'dtx_waterfall_segment_p99_ms{segment="queue_wait"}' \
            in body
    finally:
        srv.close()


def test_status_cache_s_flag_validation():
    from distributed_tensorflow_example_tpu.config import (
        Config, parse_config, validate_serving_config,
    )

    assert parse_config([]).status_cache_s == 15.0
    assert parse_config(
        ["--status_cache_s", "0"]).status_cache_s == 0.0
    validate_serving_config(Config(status_cache_s=0.0))
    with pytest.raises(ValueError, match="status_cache_s"):
        validate_serving_config(Config(status_cache_s=-1.0))


# --- engine chaos property suite (CPU jax) -------------------------------


jax = pytest.importorskip("jax")


from distributed_tensorflow_example_tpu.models import (  # noqa: E402
    transformer as tfm,
)
from distributed_tensorflow_example_tpu.serving import (  # noqa: E402
    admission as adm,
)
from distributed_tensorflow_example_tpu.serving import (  # noqa: E402
    faults as fl,
)
from distributed_tensorflow_example_tpu.serving.engine import (  # noqa: E402
    DecodeEngine,
)


@pytest.fixture(scope="module")
def lm():
    spec = tfm.TransformerSpec(
        input_size=32, num_classes=10, seq_len=32, d_model=32,
        n_heads=2, num_blocks=2, d_ff=64, objective="lm",
        vocab_size=50, causal=True)
    return spec, tfm.init(jax.random.PRNGKey(0), spec)


def test_chaos_waterfalls_sum_to_wall_per_request(lm, tmp_path):
    """The property the attribution gate holds in aggregate, proven
    per-rid under chaos: crash (→ requeue), shed (typed, span-only)
    and deadline timeout in ONE workload, and EVERY request's derived
    segments tile its submit→terminal wall within 1%."""
    spec, params = lm
    rng = np.random.RandomState(11)
    rec = spans_lib.SpanRecorder(str(tmp_path))
    eng = DecodeEngine(
        spec, params, page_size=4, max_batch=2, seed=0,
        engine_retries=2, max_queue=4,
        faults=fl.FaultPlan(crash_at_ticks=(2,)), recorder=rec)
    rids, shed = [], 0
    for i in range(8):
        prompt = rng.randint(0, 50, size=3 + (i % 4)).tolist()
        # the first prefill compile takes seconds on CPU, so a 40 ms
        # deadline deterministically times out
        dl = 40.0 if i == 3 else None
        try:
            rids.append(eng.submit(prompt, 4, deadline_ms=dl))
        except adm.ShedError:
            shed += 1
    eng.run_until_idle()
    results = [eng.result(r, timeout=60.0) for r in rids]
    rec.close()
    assert all(r is not None for r in results)

    rows = spans_lib.read_spans(rec.path)
    assert schema_lib.validate_span_file(rec.path) == []
    docs = wf_lib.waterfalls(rows)
    # every consumed rid reconstructs: accepted requests from their
    # submit row, shed ones from their span-only shed row (zero wall)
    assert len(docs) == len(rids) + shed
    assert set(rids) <= {d["rid"] for d in docs}
    for d in docs:
        assert d["complete"], (d["rid"], d)
        assert all(v >= 0.0 for v in d["segments"].values())
        _assert_tiles(d)
    summ = wf_lib.summarize(docs)
    assert summ["sum_to_wall_ok"]
    assert summ["max_residual_frac"] <= 0.01
    # the chaos actually happened: a crash re-queued someone, the
    # deadline timed out, and the bounded queue shed
    terms = summ["terminals"]
    assert terms.get("result", 0) >= 1
    assert terms.get("timeout", 0) >= 1
    assert shed >= 1
    assert any(d["requeues"] > 0 for d in docs)
    assert any(d["segments"]["requeue"] > 0 for d in docs)

    # the queue explains itself too: every submit terminated, so the
    # identity holds with zero violations
    ll = queueing_lib.queueing_report(rows)["littles_law"]
    assert ll["holds"] and ll["violations"] == 0
