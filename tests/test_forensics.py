"""Failure-forensics tests (obs/tracer, obs/anomaly, obs/flight,
obs/schema + their train-loop wiring): profiler windowing via a
stubbed jax.profiler (start/stop exactly once per window, annotations
nest), the anomaly policies on injected NaN losses, flight-recorder
ring/dump/SIGUSR1 round-trips, the chief collator, and the schema
validators that pin the telemetry formats."""

import json
import os
import signal
import sys

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.obs import anomaly as anomaly_lib
from distributed_tensorflow_example_tpu.obs import flight as flight_lib
from distributed_tensorflow_example_tpu.obs import schema as schema_lib
from distributed_tensorflow_example_tpu.obs import tracer as tracer_lib
from distributed_tensorflow_example_tpu.obs.metrics import MetricsLogger

from conftest import needs_stack  # noqa: E402


class StubProfiler:
    """Records the windowing contract instead of tracing."""

    def __init__(self, raise_on_stop: bool = False):
        self.starts = []
        self.stops = 0
        self.events = []
        self._raise_on_stop = raise_on_stop

    def start_trace(self, d):
        self.starts.append(d)
        self.events.append(("start", d))

    def stop_trace(self):
        self.stops += 1
        self.events.append(("stop", None))
        if self._raise_on_stop:
            raise RuntimeError("synthetic corrupt-trace failure")

    def _scope(self, label):
        events = self.events

        class _S:
            def __enter__(self):
                events.append(("enter", label))
                return self

            def __exit__(self, *exc):
                events.append(("exit", label))
                return False

        return _S()

    def StepTraceAnnotation(self, name, step_num=None):
        return self._scope(f"{name}:{step_num}")

    def TraceAnnotation(self, name):
        return self._scope(name)

    def start_server(self, port):
        self.events.append(("server", port))
        return ("server", port)


# --- obs.tracer -----------------------------------------------------------


def test_parse_profile_steps():
    assert tracer_lib.parse_profile_steps("") is None
    assert tracer_lib.parse_profile_steps("500:20") == (500, 20)
    assert tracer_lib.parse_profile_steps("0:1") == (0, 1)
    for bad in ("20", "a:b", "5:0", "-1:5", "1:2:3"):
        with pytest.raises(ValueError):
            tracer_lib.parse_profile_steps(bad)


def test_windowed_capture_exactly_once(tmp_path):
    """Window 5:3 over 12 host steps: start_trace at step 5, stop
    before step 8 dispatches — called exactly once each."""
    prof = StubProfiler()
    tr = tracer_lib.WindowedTracer(str(tmp_path), window=(5, 3),
                                   profiler=prof)
    tr.begin_run()  # windowed mode: must NOT start here
    assert prof.starts == []
    for step in range(12):
        tr.on_step(step)
        with tr.step_annotation(step):
            pass
    tr.stop()  # idempotent final stop
    assert len(prof.starts) == 1 and prof.stops == 1
    assert tr.windows_captured == 1
    # the trace went to <logs_path>/profile
    assert prof.starts[0] == os.path.join(str(tmp_path), "profile")
    # start fired before step 5's annotation; stop fired after the
    # last in-window step (7) and before step 8 would have dispatched
    # (post-window steps are no longer annotated at all)
    ev = prof.events
    assert ev.index(("start", prof.starts[0])) \
        < ev.index(("enter", "train:5"))
    assert ev.index(("exit", "train:7")) < ev.index(("stop", None))
    assert ("enter", "train:8") not in ev


def test_window_annotations_nest(tmp_path):
    """TraceAnnotation scopes nest inside the StepTraceAnnotation —
    enters/exits pair LIFO."""
    prof = StubProfiler()
    tr = tracer_lib.WindowedTracer(str(tmp_path), window=(0, 2),
                                   profiler=prof)
    tr.on_step(0)
    with tr.step_annotation(0):
        with tr.annotate("data_wait"):
            pass
        with tr.annotate("dispatch"):
            pass
    labels = [e for e in prof.events if e[0] in ("enter", "exit")]
    assert labels == [("enter", "train:0"),
                      ("enter", "data_wait"), ("exit", "data_wait"),
                      ("enter", "dispatch"), ("exit", "dispatch"),
                      ("exit", "train:0")]


def test_crash_mid_window_still_stops(tmp_path):
    """A run dying inside the window: the finally-path stop() closes
    the trace; a stop_trace that itself raises is swallowed (the
    original exception must not be masked)."""
    prof = StubProfiler(raise_on_stop=True)
    tr = tracer_lib.WindowedTracer(str(tmp_path), window=(1, 100),
                                   profiler=prof)
    tr.on_step(0)
    tr.on_step(1)
    assert tr.active
    tr.stop()  # must not raise despite the stub raising
    assert prof.stops == 1 and not tr.active
    tr.stop()  # idempotent
    assert prof.stops == 1


def test_whole_run_mode_exception_safe(tmp_path):
    """Legacy --profile: begin_run starts, stop() (the finally) stops
    — exactly once each, no window arithmetic involved."""
    prof = StubProfiler()
    tr = tracer_lib.WindowedTracer(str(tmp_path), whole_run=True,
                                   profiler=prof)
    tr.begin_run()
    for step in range(5):
        tr.on_step(step)  # must not re-start or stop
    tr.stop()
    assert len(prof.starts) == 1 and prof.stops == 1


def test_on_range_fast_path_granularity(tmp_path):
    """Fast path traces at program granularity: only epochs
    overlapping the window start the trace; the first program past
    the window stops it."""
    prof = StubProfiler()
    tr = tracer_lib.WindowedTracer(str(tmp_path), window=(15, 5),
                                   profiler=prof)
    for epoch in range(4):  # 10 steps per epoch
        tr.on_range(epoch * 10, (epoch + 1) * 10)
    tr.stop()
    # epoch 0 [0,10): no; epoch 1 [10,20): overlaps -> start; epoch 2
    # [20,30): past the window -> stop before dispatch
    assert len(prof.starts) == 1 and prof.stops == 1


def test_disabled_tracer_is_inert(tmp_path):
    prof = StubProfiler()
    tr = tracer_lib.WindowedTracer(str(tmp_path), window=(0, 5),
                                   enabled=False, profiler=prof)
    tr.begin_run()
    tr.on_step(0)
    with tr.step_annotation(0), tr.annotate("dispatch"):
        pass
    tr.stop()
    assert prof.events == []


def test_boundary_signals_window_edges(tmp_path):
    """boundary(step) is the host loop's drain-the-queue signal: True
    exactly when on_step(step) will open or close the window (the
    async dispatch queue must sync there or the trace captures the
    device execution of earlier steps)."""
    prof = StubProfiler()
    tr2 = tracer_lib.WindowedTracer(str(tmp_path), window=(5, 3),
                                    profiler=prof)
    edges = []
    for s in range(12):
        if tr2.boundary(s):
            edges.append(s)
        tr2.on_step(s)
    assert edges == [5, 8]
    # whole-run and disabled tracers never ask for a drain
    tr3 = tracer_lib.WindowedTracer(str(tmp_path), whole_run=True,
                                    profiler=prof)
    assert not any(tr3.boundary(s) for s in range(5))


def test_anomaly_record_loss_is_strict_json():
    """A NaN loss reaches the metrics event stream stringified, never
    as a bare NaN literal (the schema contract)."""
    fl = _StubFlight()

    class _StubLogger:
        events = []

        def log_event(self, event, **fields):
            self.events.append(fields)

    ml = _StubLogger()
    p = anomaly_lib.AnomalyPolicy("dump", flight=fl, mlogger=ml)
    p.on_step(1, loss=float("nan"), flagged=True, counts=np.array([1]))
    assert ml.events[0]["loss"] == "nan"
    assert json.dumps(ml.events[0], allow_nan=False)  # strict-safe


def test_profiler_server(tmp_path):
    prof = StubProfiler()
    tr = tracer_lib.WindowedTracer(str(tmp_path), profiler=prof)
    assert tr.start_server(0) is None
    assert tr.start_server(9999) == ("server", 9999)


# --- obs.anomaly ----------------------------------------------------------


def test_watchdog_nonfinite_and_divergence():
    w = anomaly_lib.LossWatchdog(factor=10.0, warmup=3)
    assert w.observe(0, float("nan")) == "nonfinite_loss"
    assert w.observe(1, float("inf")) == "nonfinite_loss"
    for i in range(4):
        assert w.observe(i, 2.0) is None  # warmup absorbs
    assert w.observe(10, 2.1) is None
    assert w.observe(11, 50.0) == "divergence"
    # the flagged loss did NOT drag the EMA up
    assert w.ema == pytest.approx(2.0, rel=0.1)
    assert w.observe(12, 2.0) is None


def test_watchdog_no_flags_during_warmup():
    w = anomaly_lib.LossWatchdog(factor=2.0, warmup=50)
    # wild but finite swings during warmup stay unflagged
    for i, loss in enumerate([1.0, 30.0, 0.1, 500.0]):
        assert w.observe(i, loss) is None


class _StubFlight:
    def __init__(self):
        self.anomalies = []
        self.dumps = []

    def record_anomaly(self, step, **fields):
        self.anomalies.append(dict(step=step, **fields))

    def dump(self, reason, exc=None):
        self.dumps.append(reason)
        return "/dev/null"


def test_policy_halt_records_then_raises():
    fl = _StubFlight()
    p = anomaly_lib.AnomalyPolicy("halt", leaf_names=["['W1']", "['b1']"],
                                  flight=fl)
    assert p.on_step(1, loss=1.0, flagged=False) is False
    with pytest.raises(anomaly_lib.AnomalyError, match="nonfinite_grads"):
        p.on_step(2, loss=float("nan"), flagged=True,
                  counts=np.array([7, 0]))
    assert p.anomalies == 1
    assert fl.anomalies and fl.anomalies[0]["blame"] == {"['W1']": 7}
    assert fl.anomalies[0]["policy"] == "halt"


def test_policy_dump_continues_and_bounds_writes():
    fl = _StubFlight()
    p = anomaly_lib.AnomalyPolicy("dump", flight=fl, max_dump_writes=2)
    for step in range(5):
        assert p.on_step(step, loss=float("nan"), flagged=True,
                         counts=np.array([1]))
    assert p.anomalies == 5
    assert fl.dumps == ["anomaly", "anomaly"]  # bounded
    assert p.skipped_steps == 0


def test_policy_skip_accounting():
    p = anomaly_lib.AnomalyPolicy("skip", flight=_StubFlight())
    p.on_step(1, loss=float("nan"), flagged=True, counts=np.array([3]))
    p.on_step(2, loss=1.0, flagged=False)
    p.on_step(3, loss=float("nan"), flagged=True, counts=np.array([2]))
    assert p.summary() == {"anomalies": 2, "skipped_steps": 2}


def test_policy_on_epoch_fast_path():
    """Post-hoc fast-path check: non-finite entries in the returned
    cost array are per-step anomalies (and the skip accounting)."""
    fl = _StubFlight()
    p = anomaly_lib.AnomalyPolicy("skip", flight=fl)
    costs = np.array([1.0, 2.0, float("nan"), 1.5, float("inf")])
    bad = p.on_epoch(0, costs, base_step=100)
    assert bad == 2
    assert p.skipped_steps == 2
    assert [a["step"] for a in fl.anomalies] == [103, 105]


def test_policy_rejects_bad_mode():
    with pytest.raises(ValueError):
        anomaly_lib.AnomalyPolicy("explode")
    with pytest.raises(ValueError):
        anomaly_lib.AnomalyPolicy("")


# --- obs.flight -----------------------------------------------------------


def test_flight_ring_keeps_last_k(tmp_path):
    fr = flight_lib.FlightRecorder(str(tmp_path), 0, capacity=4)
    for i in range(10):
        fr.record_step(i, epoch=0, batch_index=i)
    path = fr.dump("test")
    doc = flight_lib.read_flight(path)
    assert [r["step"] for r in doc["steps"]] == [6, 7, 8, 9]
    assert doc["last_step"] == 9
    assert doc["proc"] == 0 and doc["reason"] == "test"
    assert schema_lib.validate_flight_dump(doc) == []


def test_flight_window_ring_survives_step_churn(tmp_path):
    """Enriched window records live in their own ring: thousands of
    bare per-step appends must not evict the few records carrying the
    post-mortem signal (loss/timing)."""
    fr = flight_lib.FlightRecorder(str(tmp_path), 0, capacity=4,
                                   window_capacity=3)
    fr.record_window(100, cost=1.5, timing={"steps": 100})
    for i in range(101, 400):
        fr.record_step(i, epoch=0, batch_index=i)
    fr.record_window(200, cost=1.2, timing={"steps": 100})
    doc = flight_lib.read_flight(fr.dump("crash"))
    assert [w["step"] for w in doc["windows"]] == [100, 200]
    assert doc["windows"][0]["cost"] == 1.5
    assert [r["step"] for r in doc["steps"]] == [396, 397, 398, 399]
    assert schema_lib.validate_flight_dump(doc) == []


def test_flight_attach_loss_backfills_ring(tmp_path):
    """The anomaly drain learns a step's loss after dispatch; it
    backfills the matching ring record (and quietly no-ops for a
    record already evicted)."""
    fr = flight_lib.FlightRecorder(str(tmp_path), 0, capacity=4)
    for i in range(1, 7):
        fr.record_step(i, epoch=0)
    fr.attach_loss(5, 2.25)
    fr.attach_loss(1, 9.9)  # already evicted — no-op
    recs = {r["step"]: r for r in fr.records}
    assert recs[5]["loss"] == 2.25
    assert "loss" not in recs[3]


def test_flight_dump_is_strict_json_with_nonfinite(tmp_path):
    """NaN/Inf losses must not produce a dump that a standards
    parser rejects."""
    fr = flight_lib.FlightRecorder(str(tmp_path), 0, capacity=4)
    fr.record_step(1, cost=float("nan"))
    fr.record_anomaly(1, reasons=["nonfinite_loss"], policy="dump",
                      loss=float("inf"))
    path = fr.dump("anomaly")
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    doc = json.loads(raw)  # strict-parseable
    assert doc["steps"][0]["cost"] == "nan"
    assert schema_lib.validate_flight_dump(doc) == []


def test_flight_dump_carries_exception_and_env(tmp_path):
    fr = flight_lib.FlightRecorder(str(tmp_path), 0,
                                   config={"seed": 1, "lr": 5e-4})
    try:
        raise RuntimeError("mid-step boom")
    except RuntimeError as e:
        path = fr.dump("crash", exc=e)
    doc = flight_lib.read_flight(path)
    assert doc["exception"]["type"] == "RuntimeError"
    assert "mid-step boom" in doc["exception"]["message"]
    assert any("mid-step boom" in ln
               for ln in doc["exception"]["traceback"])
    env = doc["env"]
    assert env["pid"] == os.getpid()
    assert env["config"]["seed"] == 1
    assert "python" in env


def test_flight_excepthook_chains(tmp_path):
    fr = flight_lib.FlightRecorder(str(tmp_path), 1)
    fr.record_step(42)
    seen = []
    old = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        fr.install()
        try:
            raise ValueError("unhandled")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        doc = flight_lib.read_flight(fr.path)
        assert doc["reason"] == "crash"
        assert doc["exception"]["type"] == "ValueError"
        assert seen, "previous excepthook must still run"
    finally:
        fr.uninstall()
        sys.excepthook = old
    assert sys.excepthook is old  # uninstall restored the chain


def test_flight_sigusr1_dump_and_stacks(tmp_path):
    """kill -USR1: flight dump + faulthandler stack file from a live
    process, handlers restored on uninstall."""
    fr = flight_lib.FlightRecorder(str(tmp_path), 0, capacity=8)
    fr.record_step(7, epoch=0)
    prev = signal.getsignal(signal.SIGUSR1)
    fr.install()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        # the handler runs at the next bytecode boundary
        for _ in range(100):
            if os.path.exists(fr.path):
                break
        doc = flight_lib.read_flight(fr.path)
        assert doc["reason"] == "sigusr1"
        assert doc["last_step"] == 7
        assert schema_lib.validate_flight_file(fr.path) == []
        stacks = open(fr.stacks_path).read()
        assert "test_flight_sigusr1_dump_and_stacks" in stacks
    finally:
        fr.uninstall()
    assert signal.getsignal(signal.SIGUSR1) == prev


def test_flight_dump_never_raises(tmp_path, monkeypatch):
    fr = flight_lib.FlightRecorder(str(tmp_path), 0)
    monkeypatch.setattr(flight_lib.os, "replace",
                        lambda *a: (_ for _ in ()).throw(OSError("full")))
    assert fr.dump("crash") is None  # degraded, not raised


def test_collate_post_mortem(tmp_path):
    """Chief collator: per-proc last step/reason, the step spread
    (blast radius) and merged anomalies, written to report.json."""
    for proc, last, reason in ((0, 120, "crash"), (1, 90, "sigusr1")):
        fr = flight_lib.FlightRecorder(str(tmp_path), proc, capacity=4)
        fr.record_step(last, epoch=0)
        if proc == 1:
            fr.record_anomaly(88, reasons=["divergence"], policy="halt")
        fr.dump(reason)
    rep = flight_lib.collate(str(tmp_path))
    assert rep["proc_count"] == 2
    assert rep["min_last_step"] == 90 and rep["max_last_step"] == 120
    assert rep["step_spread"] == 30
    assert rep["slowest_proc"] == "1"
    assert [a["step"] for a in rep["anomalies"]] == [88]
    on_disk = json.load(
        open(os.path.join(str(tmp_path), "flight", "report.json")))
    assert on_disk["step_spread"] == 30


def test_collate_empty(tmp_path):
    rep = flight_lib.collate(str(tmp_path))
    assert rep["proc_count"] == 0 and rep["step_spread"] is None


# --- obs.schema -----------------------------------------------------------


def _full_window_fields():
    """Every field the train loop's metrics_row emits (docs schema)."""
    return dict(step=100, epoch=0, cost=1.5, path="host", steps=50,
                window_wall_s=0.4, step_time_p50_ms=8.0,
                step_time_p95_ms=9.5, step_time_max_ms=22.0,
                data_wait_s=0.01, h2d_s=0.02, dispatch_s=0.1,
                device_wait_s=0.2, ckpt_s=0.0, host_s=0.07,
                examples_per_sec=1950.0, tokens_per_sec=None,
                model_flops_per_step=4.8e6, tflops_per_sec=0.012,
                mfu=None)


def test_schema_validates_real_metrics_file(tmp_path):
    m = MetricsLogger(str(tmp_path), process_index=0)
    m.log_window(**_full_window_fields())
    m.log_event("compile", what="train_step", dispatch_wall_s=0.7)
    m.log_event("anomaly", step=3, reasons=["divergence"], policy="dump")
    m.close()
    assert schema_lib.validate_metrics_file(m.path) == []


def test_schema_flags_drift(tmp_path):
    """A renamed/missing/mistyped field fails loudly — the contract
    the dashboards depend on."""
    fields = _full_window_fields()
    del fields["step_time_p95_ms"]          # dropped field
    fields["data_wait_s"] = "0.01"          # wrong type
    m = MetricsLogger(str(tmp_path), process_index=0)
    m.log_window(**fields)
    m.close()
    errs = schema_lib.validate_metrics_file(m.path)
    assert any("step_time_p95_ms" in e and "missing" in e for e in errs)
    assert any("data_wait_s" in e and "type" in e for e in errs)
    # unknown kinds are drift too
    assert schema_lib.validate_metrics_row(
        {"kind": "windoww", "t": 1.0, "proc": 0})
    # and non-JSON lines
    with open(m.path, "a") as f:
        f.write("not json\n")
    assert any("not JSON" in e
               for e in schema_lib.validate_metrics_file(m.path))


def test_schema_flight_records_checked(tmp_path):
    doc = {"version": schema_lib.SCHEMA_VERSION, "proc": 0,
           "reason": "crash", "t": 1.0,
           "last_step": 5, "steps": [{"step": 5, "t": 1.0}],
           "windows": [{"step": 5, "t": 1.0, "cost": 1.0}],
           "anomalies": [{"step": 5, "t": 1.0, "reasons": ["x"],
                          "policy": "halt"}],
           "env": {}}
    assert schema_lib.validate_flight_dump(doc) == []
    doc["steps"].append({"t": 1.0})  # record missing its step id
    doc["anomalies"][0].pop("policy")
    errs = schema_lib.validate_flight_dump(doc)
    assert any("steps[1]" in e and "step" in e for e in errs)
    assert any("anomalies[0]" in e and "policy" in e for e in errs)


# --- end-to-end through train.loop ---------------------------------------


def _tiny(tmp_path, **kw):
    from distributed_tensorflow_example_tpu.config import Config

    return Config(training_epochs=1, batch_size=16, dataset="synthetic",
                  synthetic_train_size=160, synthetic_test_size=32,
                  logs_path=str(tmp_path), frequency=5, summaries=False,
                  fast_loop=False, compilation_cache="", **kw)


@needs_stack
def test_flag_validation():
    from distributed_tensorflow_example_tpu.config import Config
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="profile_steps"):
        run(Config(profile_steps="oops"))
    with pytest.raises(ValueError, match="replaces"):
        run(Config(profile=True, profile_steps="5:2"))
    with pytest.raises(ValueError, match="debug_nans"):
        run(Config(on_anomaly="halt", debug_nans=True))
    with pytest.raises(ValueError, match="skip"):
        run(Config(on_anomaly="skip", fsdp=True))
    with pytest.raises(ValueError, match="on_anomaly"):
        run(Config(on_anomaly="explode"))
    with pytest.raises(ValueError, match="flight_steps"):
        run(Config(flight=True, flight_steps=0))
    with pytest.raises(ValueError, match="anomaly_factor"):
        run(Config(on_anomaly="halt", anomaly_factor=1.0))


@needs_stack
def test_anomaly_halt_leaves_flight_dump(tmp_path):
    """Injected blowup (lr=1e30): the run raises AnomalyError and
    leaves a parseable flight/<proc>.json with per-leaf blame."""
    from distributed_tensorflow_example_tpu.train.loop import run

    # naive_ce (the reference's unstable log(softmax)) + a huge lr:
    # step 2's saturated softmax yields inf/inf = NaN loss and NaN
    # grads — the deterministic blowup injection
    with pytest.raises(anomaly_lib.AnomalyError):
        run(_tiny(tmp_path, learning_rate=1e30, naive_ce=True,
                  on_anomaly="halt"))
    path = os.path.join(str(tmp_path), "flight", "0.json")
    doc = flight_lib.read_flight(path)
    assert schema_lib.validate_flight_dump(doc) == []
    assert doc["reason"] == "anomaly_halt"
    assert doc["exception"]["type"] == "AnomalyError"
    assert doc["anomalies"], "the anomaly must be in the dump"
    assert doc["steps"], "ring records must be in the dump"
    # per-leaf blame names resolve to real param leaves when the
    # gradients (not just the loss) went non-finite
    blames = [a["blame"] for a in doc["anomalies"] if a.get("blame")]
    for b in blames:
        assert all(k.startswith("[") for k in b)
    # the chief collated a post-mortem report
    rep = json.load(open(os.path.join(str(tmp_path), "flight",
                                      "report.json")))
    assert rep["procs"]["0"]["reason"] == "anomaly_halt"


@needs_stack
def test_anomaly_halt_forces_host_loop(tmp_path):
    """halt + the default fast loop: a whole-run device program can
    only be judged after it completed, so halt forces the host loop —
    the run stops promptly (not after every epoch ran) and the dump's
    ring records carry the drained losses."""
    from distributed_tensorflow_example_tpu.config import Config
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(anomaly_lib.AnomalyError):
        run(Config(training_epochs=3, batch_size=16,
                   dataset="synthetic", synthetic_train_size=160,
                   synthetic_test_size=32, logs_path=str(tmp_path),
                   frequency=5, summaries=False, compilation_cache="",
                   naive_ce=True, learning_rate=1e30,
                   on_anomaly="halt"))  # fast_loop left at default True
    doc = flight_lib.read_flight(
        os.path.join(str(tmp_path), "flight", "0.json"))
    assert doc["reason"] == "anomaly_halt"
    # halted inside epoch 0 — far before the 30-step whole run ended
    assert doc["last_step"] < 10
    # the anomaly drain backfilled the fetched loss into the ring
    assert any("loss" in r for r in doc["steps"])


@needs_stack
def test_anomaly_skip_accounts_and_completes(tmp_path):
    """--on_anomaly=skip: the blowup is skipped on-device, the run
    completes, skipped steps are accounted in the result."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(_tiny(tmp_path, learning_rate=1e30, naive_ce=True,
                    on_anomaly="skip"))
    assert res["anomalies"] >= 1
    assert res["skipped_steps"] >= 1
    assert res["steps"] == 10  # every step attempted


@needs_stack
def test_crash_mid_step_dumps_flight_and_stops_trace(tmp_path, monkeypatch):
    """Killing the run mid-step (injected exception on step 4): the
    flight dump exists with the exception, and the windowed profiler
    trace that was open is STOPPED exactly once (stubbed profiler)."""
    import jax

    from distributed_tensorflow_example_tpu.train import loop as loop_mod

    prof = StubProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", prof.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", prof.stop_trace)
    monkeypatch.setattr(jax.profiler, "StepTraceAnnotation",
                        prof.StepTraceAnnotation)
    monkeypatch.setattr(jax.profiler, "TraceAnnotation",
                        prof.TraceAnnotation)

    real_build = loop_mod.step_lib.build_train_step

    def crashing_build(*a, **kw):
        step = real_build(*a, **kw)
        calls = {"n": 0}

        def wrapped(*sa, **skw):
            calls["n"] += 1
            if calls["n"] == 4:
                raise RuntimeError("injected mid-step crash")
            return step(*sa, **skw)

        return wrapped

    monkeypatch.setattr(loop_mod.step_lib, "build_train_step",
                        crashing_build)
    with pytest.raises(RuntimeError, match="injected"):
        loop_mod.run(_tiny(tmp_path, flight=True, profile_steps="2:50"))
    # flight dump with the crash context
    doc = flight_lib.read_flight(
        os.path.join(str(tmp_path), "flight", "0.json"))
    assert schema_lib.validate_flight_dump(doc) == []
    assert doc["reason"] == "crash"
    assert "injected mid-step crash" in doc["exception"]["message"]
    assert doc["last_step"] == 3  # three completed steps in the ring
    # the open trace window was terminated by the finally, exactly once
    assert len(prof.starts) == 1 and prof.stops == 1


@needs_stack
def test_profile_steps_windowed_run(tmp_path, monkeypatch):
    """A clean host-path run with --profile_steps 3:2: start/stop
    exactly once, step annotations only inside the window's span, and
    the run result reports the captured window."""
    import jax

    from distributed_tensorflow_example_tpu.train.loop import run

    prof = StubProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", prof.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", prof.stop_trace)
    monkeypatch.setattr(jax.profiler, "StepTraceAnnotation",
                        prof.StepTraceAnnotation)
    monkeypatch.setattr(jax.profiler, "TraceAnnotation",
                        prof.TraceAnnotation)
    res = run(_tiny(tmp_path, profile_steps="3:2"))
    assert len(prof.starts) == 1 and prof.stops == 1
    assert res["profile_windows"] == 1
    ev = prof.events
    assert ev.index(("start", prof.starts[0])) \
        < ev.index(("enter", "train:3"))
    assert ev.index(("exit", "train:4")) < ev.index(("stop", None))
    assert ("enter", "train:5") not in ev  # window closed, no scopes


@needs_stack
def test_profile_window_past_training_end_closes_before_eval(tmp_path,
                                                             monkeypatch):
    """A window still open when training ends (8:50 on a 10-step run)
    is closed BEFORE final eval/sampling — the capture is the
    requested steps, not the shutdown tail."""
    import jax

    from distributed_tensorflow_example_tpu.train.loop import run

    prof = StubProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", prof.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", prof.stop_trace)
    monkeypatch.setattr(jax.profiler, "StepTraceAnnotation",
                        prof.StepTraceAnnotation)
    monkeypatch.setattr(jax.profiler, "TraceAnnotation",
                        prof.TraceAnnotation)
    res = run(_tiny(tmp_path, profile_steps="8:50"))
    assert len(prof.starts) == 1 and prof.stops == 1
    assert res["profile_windows"] == 1
    # no eval scope inside the capture: the trace closed first
    assert ("enter", "eval") not in prof.events


@needs_stack
def test_flight_records_through_run(tmp_path):
    """--flight + --metrics on a clean run: no dump (nothing failed),
    but a SIGUSR1-style manual dump carries window records with the
    timing split."""
    from distributed_tensorflow_example_tpu.train import loop as loop_mod

    res = loop_mod.run(_tiny(tmp_path, flight=True, metrics=True,
                             log_every=5))
    assert res["anomalies"] == 0
    # a clean run leaves no dump
    assert not os.path.exists(
        os.path.join(str(tmp_path), "flight", "0.json"))
