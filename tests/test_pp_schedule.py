"""Golden tests for the pure-Python pipeline tick tables (ISSUE 8).

parallel/pp_schedule is the ONE derivation of the gpipe / 1f1b /
interleaved-1F1B schedules: the kernel loop
(transformer.pipeline_value_and_grad_1f1b) compiles the table
literally, and the bubble bench (bench_pp_memory) reports its tick
accounting.  These tests pin the schedule with NO mesh and NO jax —
tier-1 on every environment — so a schedule bug is caught structurally
before any numerical test could blame the wrong layer.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from distributed_tensorflow_example_tpu.parallel import pp_schedule as pps

# the (p, v, m) matrix the structural checks sweep: every phase shape
# (warmup/steady/drain), v == 1 degeneration, deep p, wide m, and the
# minimum m == p interleaved case
MATRIX = [
    (2, 1, 1), (2, 1, 4), (3, 1, 6), (4, 1, 16),
    (2, 2, 2), (2, 2, 4), (2, 4, 4), (3, 2, 6), (4, 2, 8),
    (4, 2, 16), (4, 4, 8), (4, 4, 16),
]


def test_import_is_pure_python():
    """The tick tables import with NO jax anywhere in the process —
    the property the golden tests and the bench's CPU path lean on
    (parallel/__init__ resolves its jax members lazily)."""
    code = (
        "import sys\n"
        "from distributed_tensorflow_example_tpu.parallel import "
        "pp_schedule\n"
        "pp_schedule.check_table("
        "pp_schedule.interleaved_1f1b_table(2, 2, 4))\n"
        "assert 'jax' not in sys.modules, 'pp_schedule pulled in jax'\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True, cwd=_REPO)


@pytest.mark.parametrize("p,v,m", MATRIX)
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_structural_invariants(schedule, p, v, m):
    """check_table: exactly-once coverage, one-tick-earlier producer
    for every hop (incl. the v>1 chunk wrap), backward-after-forward,
    and the stash-slot reuse discipline under ``min(vM, 2pv-1)``."""
    if schedule == "1f1b" and p < 2:
        pytest.skip("1f1b needs p >= 2")
    pps.check_table(pps.schedule_table(schedule, p, v, m))


def test_classic_1f1b_degeneration():
    """v == 1 collapses to the classic fused 1F1B: ``m + 2(p-1)``
    ticks, stage s forwards microbatch m at tick ``m + s`` and
    backwards it at tick ``m + 2(p-1) - s``."""
    p, m = 3, 6
    t = pps.interleaved_1f1b_table(p, 1, m)
    assert t.ticks == m + 2 * (p - 1)
    assert t.stash_cap == min(m, 2 * p - 1)
    for tick in range(t.ticks):
        for s in range(p):
            frow, brow = t.fwd[tick], t.bwd[tick]
            if frow is not None and frow[s].live:
                assert tick == frow[s].microbatch + s
                assert frow[s].chunk == 0
            if brow is not None and brow[s].live:
                assert tick == brow[s].microbatch + 2 * (p - 1) - s


def test_interleaved_forward_order_is_megatron():
    """p=2, v=2, m=4: stage 0's forward execution order round-robins
    chunks over groups of p microbatches — the Megatron interleaved
    pattern, pinned exactly."""
    t = pps.interleaved_1f1b_table(2, 2, 4)
    order = []
    for tick in range(t.ticks):
        row = t.fwd[tick]
        if row is not None and row[0].live:
            order.append((row[0].chunk, row[0].microbatch))
    assert order == [(0, 0), (0, 1), (1, 0), (1, 1),
                     (0, 2), (0, 3), (1, 2), (1, 3)]


def test_warmup_and_drain_specialization():
    """The first ``pv - 1`` ticks are forward-only and the trailing
    ``pv - 1`` backward-only — the specialization that makes a
    lockstep SPMD realization actually cheaper in warmup/drain (a
    dead fused tick would still cost fwd+bwd compute)."""
    for p, v, m in [(2, 1, 4), (4, 1, 16), (2, 2, 4), (4, 2, 16),
                    (4, 4, 16)]:
        t = pps.interleaved_1f1b_table(p, v, m)
        c = pps.tick_counts(t)
        assert c["fwd_only_ticks"] == p * v - 1, (p, v, m)
        assert c["bwd_only_ticks"] == p * v - 1, (p, v, m)
        assert (c["fwd_only_ticks"] + c["bwd_only_ticks"]
                + c["combined_ticks"] == t.ticks)
        # every tick in the table is emitted (no fully-dead ticks)
        assert all(f is not None or b is not None
                   for f, b in zip(t.fwd, t.bwd))


@pytest.mark.parametrize("p,v,m", [pvm for pvm in MATRIX
                                   if pvm[0] >= 2])
def test_bubble_fraction_closed_form(p, v, m):
    """Both schedules measure the same closed-form bubble at a given
    v — ``(p-1)/(vm + p - 1)`` — so interleaving is the lever: v > 1
    shrinks it ~v-fold (Narayanan et al.)."""
    for schedule in ("gpipe", "1f1b"):
        bf = pps.bubble_fraction(pps.schedule_table(schedule, p, v, m))
        expect = (p - 1) / (v * m + p - 1)
        assert bf["bubble_fraction"] == pytest.approx(expect, abs=1e-4)
        assert bf["ideal_ticks"] == pytest.approx(3.0 * m)
        assert bf["bubble_fraction"] == pytest.approx(
            1.0 - bf["ideal_ticks"] / bf["measured_ticks"], abs=1e-4)


def test_bubble_bench_acceptance_numbers():
    """The bench row's exact numbers at its default shape (p=4, m=16):
    interleaved strictly below plain 1f1b, and interleaved
    measured-vs-ideal within 10% — the ISSUE 8 acceptance line."""
    p, m = 4, 16
    plain = pps.bubble_fraction(pps.interleaved_1f1b_table(p, 1, m))
    v2 = pps.bubble_fraction(pps.interleaved_1f1b_table(p, 2, m))
    v4 = pps.bubble_fraction(pps.interleaved_1f1b_table(p, 4, m))
    assert plain["bubble_fraction"] == pytest.approx(0.1579, abs=1e-4)
    assert v2["bubble_fraction"] == pytest.approx(0.0857, abs=1e-4)
    assert v4["bubble_fraction"] == pytest.approx(0.0448, abs=1e-4)
    assert v2["bubble_fraction"] < plain["bubble_fraction"]
    assert v4["bubble_fraction"] < v2["bubble_fraction"]
    for bf in (v2, v4):
        assert bf["measured_ticks"] / bf["ideal_ticks"] < 1.10


def test_stash_cap_and_peak_liveness():
    """``stash_cap = min(vm, 2pv-1)`` is the RING size the kernel's
    ``unit % cap`` slot addressing needs (a chunk-0 unit's backward
    retires (v-1)p units later in the reverse traversal, so modulo
    reuse demands the full 2pv-1 even though fewer stashes are ever
    simultaneously live); the true peak liveness is ``p(v+1) - 1`` on
    stage 0 — at v == 1 the two coincide at the classic 2p-1.  Both
    facts pinned: peak == p(v+1)-1 <= cap, equality exactly at v==1."""
    for p, v, m in [(2, 1, 4), (4, 1, 16), (2, 2, 4), (4, 2, 16),
                    (4, 4, 16)]:
        t = pps.interleaved_1f1b_table(p, v, m)
        cap = t.stash_cap
        assert cap == min(v * m, 2 * p * v - 1)
        peak = 0
        for s in range(p):
            live = 0
            for tick in range(t.ticks):
                # the kernel writes the stash in the fwd sub-slot and
                # retires in the SAME tick's bwd sub-slot: count the
                # write before the read
                if t.fwd[tick] is not None and t.fwd[tick][s].live:
                    live += 1
                peak = max(peak, live)
                if t.bwd[tick] is not None and t.bwd[tick][s].live:
                    live -= 1
        assert peak == min(v * m, p * (v + 1) - 1), (p, v, m, peak)
        assert peak <= cap
        if v == 1:
            assert peak == cap


def test_head_marks_exactly_the_loss_units():
    """Exactly one head unit per microbatch: last stage, last chunk —
    where the kernel takes the loss and collects the stats row."""
    t = pps.interleaved_1f1b_table(4, 2, 8)
    heads = set()
    for tick in range(t.ticks):
        row = t.fwd[tick]
        if row is None:
            continue
        for s, e in enumerate(row):
            if e.live and e.head:
                assert s == t.n_stages - 1
                assert e.chunk == t.virtual - 1
                heads.add(e.microbatch)
    assert heads == set(range(t.microbatches))


def test_unit_maps_roundtrip():
    for p, v, m in MATRIX:
        for ts in range(v * m):
            c, mb = pps.fwd_unit(ts, p, v)
            assert 0 <= c < v and 0 <= mb < m
            assert pps.fwd_ts(c, mb, p, v) == ts


def test_validation_errors():
    with pytest.raises(ValueError, match="n_stages=0"):
        pps.gpipe_table(0, 1, 4)
    with pytest.raises(ValueError, match="virtual=0"):
        pps.gpipe_table(2, 0, 4)
    with pytest.raises(ValueError, match="microbatches=0"):
        pps.gpipe_table(2, 1, 0)
    with pytest.raises(ValueError, match="divisible"):
        pps.interleaved_1f1b_table(2, 2, 3)
    with pytest.raises(ValueError, match="nothing to interleave"):
        pps.gpipe_table(1, 2, 2)
    with pytest.raises(ValueError, match="1f1b needs n_stages >= 2"):
        pps.interleaved_1f1b_table(1, 1, 4)
    with pytest.raises(ValueError, match="unknown schedule"):
        pps.schedule_table("zb-h1", 2, 1, 4)


def test_gpipe_table_is_forward_only():
    t = pps.gpipe_table(4, 2, 8)
    assert all(b is None for b in t.bwd)
    assert t.ticks == 2 * 8 + 4 - 1
    assert t.stash_cap == 2 * 8  # jax.grad holds every microbatch
