"""Checkpoint round-trip tests (SURVEY.md §5 checkpoint/resume)."""

import jax
import numpy as np

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models.mlp import MLPSpec
from distributed_tensorflow_example_tpu.train.optim import make_optimizer
from distributed_tensorflow_example_tpu.train.state import create_train_state
from distributed_tensorflow_example_tpu.utils import checkpoint as C

SPEC = MLPSpec(input_size=8, hidden_sizes=(6,), num_classes=3)


def test_roundtrip_sgd(tmp_path):
    opt = make_optimizer(Config(optimizer="sgd"))
    state = create_train_state(jax.random.PRNGKey(3), SPEC, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=42, epoch=2)
    restored, step, epoch = C.restore_checkpoint(path, state)
    assert (step, epoch) == (42, 2)
    for k in state.params:
        np.testing.assert_array_equal(
            np.asarray(state.params[k]), np.asarray(restored.params[k])
        )


def test_roundtrip_adam_opt_state(tmp_path):
    opt = make_optimizer(Config(optimizer="adam"))
    state = create_train_state(jax.random.PRNGKey(3), SPEC, opt)
    # make opt state non-trivial
    g = jax.tree.map(lambda p: p * 0.01, state.params)
    new_p, new_o = opt.update(g, state.opt_state, state.params)
    state = state.replace(params=new_p, opt_state=new_o)
    path = C.save_checkpoint(str(tmp_path), state, step=1, epoch=0)
    restored, _, _ = C.restore_checkpoint(path, state)
    np.testing.assert_array_equal(
        np.asarray(state.opt_state["mu"]["W1"]), np.asarray(restored.opt_state["mu"]["W1"])
    )
    assert int(restored.opt_state["count"]) == 1


def test_latest_checkpoint_picks_highest(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    C.save_checkpoint(str(tmp_path), state, step=10, epoch=0)
    p2 = C.save_checkpoint(str(tmp_path), state, step=200, epoch=3)
    assert C.latest_checkpoint(str(tmp_path)) == p2
    assert C.latest_checkpoint(str(tmp_path / "nope")) is None


def test_shape_mismatch_rejected(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=1, epoch=0)
    other = create_train_state(
        jax.random.PRNGKey(0), MLPSpec(input_size=9, hidden_sizes=(6,), num_classes=3), opt
    )
    import pytest

    with pytest.raises((ValueError, KeyError)):
        C.restore_checkpoint(path, other)


def test_qkv_layout_migration(tmp_path):
    """Transformer checkpoints written before the Megatron-TP qkv
    re-layout ((d, 3d)/(3d,) -> (d, 3, d)/(3, d)) restore by reshape —
    the flat row-major order is identical (q|k|v column blocks)."""
    import numpy as np

    from distributed_tensorflow_example_tpu.models.transformer import (
        TransformerSpec)

    spec = TransformerSpec(input_size=64, seq_len=8, d_model=16,
                           n_heads=2, num_blocks=1, d_ff=32)
    opt = make_optimizer(Config(model="transformer", optimizer="adam"))
    state = create_train_state(jax.random.PRNGKey(0), spec, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=7, epoch=2)
    # rewrite the archive with the PRE-r3 flat qkv layout
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    rewrote = 0
    for k in list(data):
        if k.endswith("Wqkv"):
            d = data[k].shape[0]
            data[k] = data[k].reshape(d, 3 * data[k].shape[-1])
            rewrote += 1
        elif k.endswith("bqkv"):
            data[k] = data[k].reshape(-1)
            rewrote += 1
    assert rewrote >= 3  # params + both adam moments
    np.savez(path, **data)
    restored, step, epoch = C.restore_checkpoint(path, state)
    assert (step, epoch) == (7, 2)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(state)[0],
            jax.tree_util.tree_flatten_with_path(restored)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_leaves_roundtrip_both_formats(tmp_path):
    """bf16-family leaves (e.g. --adam_moments_dtype=bfloat16 slots)
    survive both on-disk formats bit-for-bit: np.savez cannot
    round-trip ml_dtypes arrays (they come back as raw void), so
    writers bit-encode into uint containers and readers view back."""
    import jax.numpy as jnp

    from distributed_tensorflow_example_tpu.train.optim import adam

    opt = adam(0.01, moments_dtype=jnp.bfloat16)
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    # non-trivial moment values
    g = jax.tree.map(lambda p: jnp.ones_like(p, jnp.float32) * 0.3,
                     state.params)
    new_p, new_o = opt.update(g, state.opt_state, state.params)
    state = state.replace(params=new_p, opt_state=new_o) \
        if hasattr(state, "replace") else type(state)(
            state.step, new_p, new_o)
    assert state.opt_state["mu"]["W1"].dtype == jnp.bfloat16

    path = C.save_checkpoint(str(tmp_path / "single"), state, 3, 1)
    restored, step, _ = C.restore_checkpoint(path, state)
    assert step == 3
    spath = C.save_checkpoint_sharded(str(tmp_path / "shard"), state, 3, 1)
    restored_s, _, _ = C.restore_checkpoint(spath, state)
    for got in (restored, restored_s):
        for k in state.opt_state["mu"]:
            a = np.asarray(got.opt_state["mu"][k])
            b = np.asarray(state.opt_state["mu"][k])
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(
                a.view(np.uint16), b.view(np.uint16))


def test_prune_checkpoints(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    for step in (5, 10, 15, 20):
        C.save_checkpoint(str(tmp_path), state, step=step, epoch=0)
    deleted = C.prune_checkpoints(str(tmp_path), keep=2)
    import os

    assert sorted(os.path.basename(d) for d in deleted) == [
        "ckpt-00000005.npz", "ckpt-00000010.npz"]
    assert C.latest_checkpoint(str(tmp_path)).endswith("ckpt-00000020.npz")
    # keep >= count and keep=0 are no-ops
    assert C.prune_checkpoints(str(tmp_path), keep=5) == []
    assert C.prune_checkpoints(str(tmp_path), keep=0) == []


def test_prune_ignores_in_flight_checkpoint(tmp_path):
    """An incomplete (in-flight) sharded checkpoint is invisible to
    retention: pruning counts only DURABLE checkpoints, so a peer
    crash mid-save can never cost the configured redundancy — the safe
    direction is transient keep+1 over-retention, never early
    deletion."""
    import json
    import os

    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    for step in (5, 10, 15):
        C.save_checkpoint(str(tmp_path), state, step=step, epoch=0)
    # simulate a mid-save sharded checkpoint: manifest names a peer
    # shard file that has not landed yet
    inflight = tmp_path / "ckpt-00000020.shards"
    os.makedirs(inflight)
    with open(inflight / "manifest.json", "w") as f:
        json.dump({"files": ["proc-00000.npz", "proc-00001.npz"],
                   "step": 20, "epoch": 0, "nprocs": 2, "leaves": {}},
                  f)
    (inflight / "proc-00000.npz").write_bytes(b"")
    deleted = C.prune_checkpoints(str(tmp_path), keep=2)
    # keep=2 durable (10, 15) + the invisible in-flight dir survive
    assert sorted(os.path.basename(d) for d in deleted) == [
        "ckpt-00000005.npz"]
    assert C.latest_checkpoint(str(tmp_path)).endswith("ckpt-00000015.npz")
    assert os.path.isdir(inflight)


def test_driver_keeps_last_n(tmp_path):
    from distributed_tensorflow_example_tpu.train.loop import run
    import os

    ckpt = str(tmp_path / "ck")
    run(Config(
        training_epochs=3, batch_size=64, hidden_sizes=(16,),
        synthetic_train_size=256, synthetic_test_size=64,
        summaries=False, frequency=8, compilation_cache="",
        checkpoint_dir=ckpt, checkpoint_every=4, keep_checkpoints=2,
    ))
    import re

    names = sorted(n for n in os.listdir(ckpt)
                   if re.fullmatch(r"ckpt-\d+\.npz", n))
    assert len(names) == 2, names


# ---------------------------------------------------------------------------
# Sharded format (--sharded_checkpoints)
# ---------------------------------------------------------------------------


def test_sharded_roundtrip_tp_mesh(devices8, tmp_path):
    """Save from a DP4xTP2-placed state with NO gather (each process
    writes its replica-0 device shards), reassemble on restore, and be
    invisible to latest_checkpoint until the manifest names only
    existing files."""
    import os

    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib

    spec = MLPSpec(input_size=16, hidden_sizes=(12, 8), num_classes=4)
    opt = make_optimizer(Config(optimizer="adam"))
    state = create_train_state(jax.random.PRNGKey(5), spec, opt)
    host = jax.tree.map(np.asarray, state)
    mesh = mesh_lib.build_mesh(4, 2)
    placed = mesh_lib.place_state(state, mesh,
                                  mesh_lib.state_pspecs(spec, opt, 2))
    path = C.save_checkpoint_sharded(str(tmp_path), placed, step=7,
                                     epoch=1, extras={"best_val": 0.5})
    assert path.endswith("ckpt-00000007.shards")
    assert C.latest_checkpoint(str(tmp_path)) == path
    assert C.load_extras(path) == {"best_val": 0.5}
    restored, step, epoch = C.restore_checkpoint(path, host)
    assert (step, epoch) == (7, 1)
    for k in host.params:
        np.testing.assert_array_equal(np.asarray(host.params[k]),
                                      np.asarray(restored.params[k]))
    # an incomplete checkpoint (manifest naming a missing file) is
    # skipped by latest_checkpoint
    import json

    man = os.path.join(path, "manifest.json")
    with open(man) as f:
        m = json.load(f)
    m["files"].append("proc-00099.npz")
    with open(man, "w") as f:
        json.dump(m, f)
    assert C.latest_checkpoint(str(tmp_path)) is None


def test_sharded_prune_removes_dirs(devices8, tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    for s in (10, 20, 30):
        C.save_checkpoint_sharded(str(tmp_path), state, step=s, epoch=0)
    C.prune_checkpoints(str(tmp_path), keep=1)
    import os

    assert sorted(os.listdir(str(tmp_path))) == ["ckpt-00000030.shards"]


def test_sharded_resume_across_dp_change(devices8, tmp_path):
    """A run saved at dp=8 resumes at dp=4: restore reassembles the
    logical arrays, placement re-shards them (VERDICT r3 next #6)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        batch_size=64, learning_rate=0.05, optimizer="adam",
        hidden_sizes=(16,), dataset="synthetic",
        synthetic_train_size=512, synthetic_test_size=128,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=str(tmp_path), sharded_checkpoints=True,
    )
    res = run(Config(training_epochs=1, data_parallel=8, **kw))
    assert res["steps"] == 8
    import os

    assert any(n.endswith(".shards") for n in os.listdir(str(tmp_path)))
    assert not any(n.endswith(".npz") for n in os.listdir(str(tmp_path)))
    res2 = run(Config(training_epochs=2, data_parallel=4, resume=True,
                      **kw))
    assert res2["steps"] == 16


def test_sharded_fsdp_resume_across_dp_change(devices8, tmp_path):
    """FSDP + sharded checkpoints: the flat [dp, chunk] layout is saved
    as-is (no host unshard in the save path) and re-laid-out on resume
    at a DIFFERENT dp."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        batch_size=64, learning_rate=0.05, optimizer="adam",
        hidden_sizes=(16,), fsdp=True, dataset="synthetic",
        synthetic_train_size=512, synthetic_test_size=128,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=str(tmp_path), sharded_checkpoints=True,
    )
    res = run(Config(training_epochs=1, data_parallel=8, **kw))
    assert res["steps"] == 8
    res2 = run(Config(training_epochs=2, data_parallel=4, resume=True,
                      **kw))
    assert res2["steps"] == 16
    assert np.isfinite(res2["final_cost"])


def test_async_sharded_save(devices8, tmp_path):
    """--async_checkpoints: the write happens on a background thread;
    wait_for_pending_saves makes it durable before the run returns."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        batch_size=64, learning_rate=0.05, optimizer="adam",
        hidden_sizes=(16,), dataset="synthetic",
        synthetic_train_size=512, synthetic_test_size=128,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=str(tmp_path), sharded_checkpoints=True,
        async_checkpoints=True, data_parallel=8,
    )
    res = run(Config(training_epochs=1, **kw))
    assert res["steps"] == 8
    assert C.latest_checkpoint(str(tmp_path)) is not None
    res2 = run(Config(training_epochs=2, resume=True, **kw))
    assert res2["steps"] == 16


def test_async_requires_sharded():
    import pytest

    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="sharded_checkpoints"):
        run(Config(async_checkpoints=True, checkpoint_dir="/tmp/x"))
