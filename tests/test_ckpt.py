"""Checkpoint round-trip tests (SURVEY.md §5 checkpoint/resume)."""

import jax
import numpy as np

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models.mlp import MLPSpec
from distributed_tensorflow_example_tpu.train.optim import make_optimizer
from distributed_tensorflow_example_tpu.train.state import create_train_state
from distributed_tensorflow_example_tpu.utils import checkpoint as C

SPEC = MLPSpec(input_size=8, hidden_sizes=(6,), num_classes=3)


def test_roundtrip_sgd(tmp_path):
    opt = make_optimizer(Config(optimizer="sgd"))
    state = create_train_state(jax.random.PRNGKey(3), SPEC, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=42, epoch=2)
    restored, step, epoch = C.restore_checkpoint(path, state)
    assert (step, epoch) == (42, 2)
    for k in state.params:
        np.testing.assert_array_equal(
            np.asarray(state.params[k]), np.asarray(restored.params[k])
        )


def test_roundtrip_adam_opt_state(tmp_path):
    opt = make_optimizer(Config(optimizer="adam"))
    state = create_train_state(jax.random.PRNGKey(3), SPEC, opt)
    # make opt state non-trivial
    g = jax.tree.map(lambda p: p * 0.01, state.params)
    new_p, new_o = opt.update(g, state.opt_state, state.params)
    state = state.replace(params=new_p, opt_state=new_o)
    path = C.save_checkpoint(str(tmp_path), state, step=1, epoch=0)
    restored, _, _ = C.restore_checkpoint(path, state)
    np.testing.assert_array_equal(
        np.asarray(state.opt_state["mu"]["W1"]), np.asarray(restored.opt_state["mu"]["W1"])
    )
    assert int(restored.opt_state["count"]) == 1


def test_latest_checkpoint_picks_highest(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    C.save_checkpoint(str(tmp_path), state, step=10, epoch=0)
    p2 = C.save_checkpoint(str(tmp_path), state, step=200, epoch=3)
    assert C.latest_checkpoint(str(tmp_path)) == p2
    assert C.latest_checkpoint(str(tmp_path / "nope")) is None


def test_shape_mismatch_rejected(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=1, epoch=0)
    other = create_train_state(
        jax.random.PRNGKey(0), MLPSpec(input_size=9, hidden_sizes=(6,), num_classes=3), opt
    )
    import pytest

    with pytest.raises((ValueError, KeyError)):
        C.restore_checkpoint(path, other)


def test_qkv_layout_migration(tmp_path):
    """Transformer checkpoints written before the Megatron-TP qkv
    re-layout ((d, 3d)/(3d,) -> (d, 3, d)/(3, d)) restore by reshape —
    the flat row-major order is identical (q|k|v column blocks)."""
    import numpy as np

    from distributed_tensorflow_example_tpu.models.transformer import (
        TransformerSpec)

    spec = TransformerSpec(input_size=64, seq_len=8, d_model=16,
                           n_heads=2, num_blocks=1, d_ff=32)
    opt = make_optimizer(Config(model="transformer", optimizer="adam"))
    state = create_train_state(jax.random.PRNGKey(0), spec, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=7, epoch=2)
    # rewrite the archive with the PRE-r3 flat qkv layout
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    rewrote = 0
    for k in list(data):
        if k.endswith("Wqkv"):
            d = data[k].shape[0]
            data[k] = data[k].reshape(d, 3 * data[k].shape[-1])
            rewrote += 1
        elif k.endswith("bqkv"):
            data[k] = data[k].reshape(-1)
            rewrote += 1
    assert rewrote >= 3  # params + both adam moments
    np.savez(path, **data)
    restored, step, epoch = C.restore_checkpoint(path, state)
    assert (step, epoch) == (7, 2)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(state)[0],
            jax.tree_util.tree_flatten_with_path(restored)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prune_checkpoints(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    for step in (5, 10, 15, 20):
        C.save_checkpoint(str(tmp_path), state, step=step, epoch=0)
    deleted = C.prune_checkpoints(str(tmp_path), keep=2)
    import os

    assert sorted(os.path.basename(d) for d in deleted) == [
        "ckpt-00000005.npz", "ckpt-00000010.npz"]
    assert C.latest_checkpoint(str(tmp_path)).endswith("ckpt-00000020.npz")
    # keep >= count and keep=0 are no-ops
    assert C.prune_checkpoints(str(tmp_path), keep=5) == []
    assert C.prune_checkpoints(str(tmp_path), keep=0) == []


def test_driver_keeps_last_n(tmp_path):
    from distributed_tensorflow_example_tpu.train.loop import run
    import os

    ckpt = str(tmp_path / "ck")
    run(Config(
        training_epochs=3, batch_size=64, hidden_sizes=(16,),
        synthetic_train_size=256, synthetic_test_size=64,
        summaries=False, frequency=8, compilation_cache="",
        checkpoint_dir=ckpt, checkpoint_every=4, keep_checkpoints=2,
    ))
    import re

    names = sorted(n for n in os.listdir(ckpt)
                   if re.fullmatch(r"ckpt-\d+\.npz", n))
    assert len(names) == 2, names
