"""Checkpoint round-trip tests (SURVEY.md §5 checkpoint/resume)."""

import jax
import numpy as np

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models.mlp import MLPSpec
from distributed_tensorflow_example_tpu.train.optim import make_optimizer
from distributed_tensorflow_example_tpu.train.state import create_train_state
from distributed_tensorflow_example_tpu.utils import checkpoint as C

SPEC = MLPSpec(input_size=8, hidden_sizes=(6,), num_classes=3)


def test_roundtrip_sgd(tmp_path):
    opt = make_optimizer(Config(optimizer="sgd"))
    state = create_train_state(jax.random.PRNGKey(3), SPEC, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=42, epoch=2)
    restored, step, epoch = C.restore_checkpoint(path, state)
    assert (step, epoch) == (42, 2)
    for k in state.params:
        np.testing.assert_array_equal(
            np.asarray(state.params[k]), np.asarray(restored.params[k])
        )


def test_roundtrip_adam_opt_state(tmp_path):
    opt = make_optimizer(Config(optimizer="adam"))
    state = create_train_state(jax.random.PRNGKey(3), SPEC, opt)
    # make opt state non-trivial
    g = jax.tree.map(lambda p: p * 0.01, state.params)
    new_p, new_o = opt.update(g, state.opt_state, state.params)
    state = state.replace(params=new_p, opt_state=new_o)
    path = C.save_checkpoint(str(tmp_path), state, step=1, epoch=0)
    restored, _, _ = C.restore_checkpoint(path, state)
    np.testing.assert_array_equal(
        np.asarray(state.opt_state["mu"]["W1"]), np.asarray(restored.opt_state["mu"]["W1"])
    )
    assert int(restored.opt_state["count"]) == 1


def test_latest_checkpoint_picks_highest(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    C.save_checkpoint(str(tmp_path), state, step=10, epoch=0)
    p2 = C.save_checkpoint(str(tmp_path), state, step=200, epoch=3)
    assert C.latest_checkpoint(str(tmp_path)) == p2
    assert C.latest_checkpoint(str(tmp_path / "nope")) is None


def test_shape_mismatch_rejected(tmp_path):
    opt = make_optimizer(Config())
    state = create_train_state(jax.random.PRNGKey(0), SPEC, opt)
    path = C.save_checkpoint(str(tmp_path), state, step=1, epoch=0)
    other = create_train_state(
        jax.random.PRNGKey(0), MLPSpec(input_size=9, hidden_sizes=(6,), num_classes=3), opt
    )
    import pytest

    with pytest.raises((ValueError, KeyError)):
        C.restore_checkpoint(path, other)
