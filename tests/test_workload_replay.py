"""Workload time machine (ISSUE 19): capture -> replay -> capacity.

Three coupled invariants under test:

- **Capture** is content-free but structure-preserving: the chained
  prompt fingerprints keep shared-prefix group structure (two prompts
  sharing a prefix share the leading digests) without retaining any
  prompt text, and ``synth_prompt`` deterministically regenerates
  replayable tokens from them.
- **Replay** is deterministic end to end: the scheduler-only
  ``replay_sim`` round-trips (replaying a workload under a recorder
  and re-capturing the emitted span stream yields the SAME workload
  id), and two seeded replays through the REAL decode engine produce
  identical typed terminals + token content with the collector's
  exactly-once join holding — the acceptance invariant.
- **Capacity** is closed-form exact: a hand-built workload reproduces
  ``sustainable_qps`` to float precision, and the ``dtx-obs
  capacity`` exit codes (0 clean / 2 bad input / 3 measured short of
  forecast) are pinned.
"""

import json
import os

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.obs import capacity as cap_lib
from distributed_tensorflow_example_tpu.obs import cli as obs_cli
from distributed_tensorflow_example_tpu.obs import schema as schema_lib
from distributed_tensorflow_example_tpu.obs import workload as wl
from distributed_tensorflow_example_tpu.serving import replay as rp


# ---------------------------------------------------------------- fingerprints


def test_fingerprint_preserves_prefix_group_structure():
    base = list(range(1, 40))
    fork = base[:wl.FINGERPRINT_BLOCK] + [63] * 20
    fa = wl.prompt_fingerprint(base)
    fb = wl.prompt_fingerprint(fork)
    # shared 16-token prefix => shared leading digest; divergent tails
    # diverge from the second block on
    assert fa[0] == fb[0]
    assert fa[1] != fb[1]
    # the chain means a changed FIRST token rewrites EVERY digest
    fc = wl.prompt_fingerprint([2] + base[1:])
    assert all(x != y for x, y in zip(fa, fc))
    # block math: ceil(len / block) digests
    assert len(fa) == (len(base) + wl.FINGERPRINT_BLOCK - 1) \
        // wl.FINGERPRINT_BLOCK


def test_synth_prompt_deterministic_and_prefix_shared():
    fp = wl.prompt_fingerprint(list(range(1, 40)))
    a = wl.synth_prompt(39, fp, vocab_size=64)
    b = wl.synth_prompt(39, fp, vocab_size=64)
    assert a == b
    assert len(a) == 39
    assert all(1 <= t < 64 for t in a)
    # two requests whose fingerprints share a leading digest get
    # token-identical leading blocks (the prefix-cache-relevant
    # structure survives regeneration)
    fp2 = list(fp)
    fp2[-1] = "0" * len(fp2[-1])
    c = wl.synth_prompt(39, fp2, vocab_size=64)
    assert a[:wl.FINGERPRINT_BLOCK] == c[:wl.FINGERPRINT_BLOCK]
    assert a != c
    # no fingerprint at all still yields a deterministic seeded prompt
    d = wl.synth_prompt(7, None, vocab_size=64, seed=1, rid=3)
    assert d == wl.synth_prompt(7, None, vocab_size=64, seed=1, rid=3)
    assert d != wl.synth_prompt(7, None, vocab_size=64, seed=1, rid=4)


# ---------------------------------------------------------------- contract


def test_synthetic_workload_validates_and_is_seeded():
    doc = wl.synthetic_workload(12, seed=0, qps=4.0)
    assert schema_lib.validate_workload(doc) == []
    assert doc["n_requests"] == 12
    assert len(doc["requests"]) == 12
    # arrival offsets are base-min normalized and sorted
    offs = [r["arrival_s"] for r in doc["requests"]]
    assert offs[0] == 0.0 and offs == sorted(offs)
    # seeded: same seed reproduces the id, another seed moves it
    assert wl.synthetic_workload(12, seed=0, qps=4.0)["workload_id"] \
        == doc["workload_id"]
    assert wl.synthetic_workload(12, seed=1, qps=4.0)["workload_id"] \
        != doc["workload_id"]


def test_synthetic_workload_shared_prefix_groups():
    doc = wl.synthetic_workload(10, seed=0, shared_prefix_frac=1.0,
                                prefix_len=wl.FINGERPRINT_BLOCK)
    heads = {r["fingerprint"][0] for r in doc["requests"]}
    assert len(heads) == 1  # every request opens with the SAME prefix
    doc2 = wl.synthetic_workload(10, seed=0, shared_prefix_frac=0.0)
    heads2 = {r["fingerprint"][0] for r in doc2["requests"]}
    assert len(heads2) > 1


def test_validate_workload_rejects_malformed():
    assert schema_lib.validate_workload({}) != []
    doc = wl.synthetic_workload(3, seed=0)
    bad = json.loads(json.dumps(doc))
    bad["requests"][1]["arrival_s"] = "soon"
    assert schema_lib.validate_workload(bad) != []
    bad2 = json.loads(json.dumps(doc))
    del bad2["requests"][0]["max_new_tokens"]
    assert schema_lib.validate_workload(bad2) != []
    bad3 = json.loads(json.dumps(doc))
    bad3["kind"] = "snapshot"
    assert schema_lib.validate_workload(bad3) != []


def test_write_load_roundtrip(tmp_path):
    doc = wl.synthetic_workload(5, seed=2)
    p = str(tmp_path / "w.json")
    wl.write_workload(doc, p)
    assert wl.load_workload(p) == doc
    # dtx-obs validate understands the workload kind
    assert obs_cli.main(["validate", p]) == 0


# ---------------------------------------------------------------- replay (sim)


def test_replay_sim_deterministic_and_identity():
    doc = wl.synthetic_workload(8, seed=0, qps=0.5, mean_prompt=16,
                                mean_new=8)
    a = rp.replay_sim(doc, num_pages=33, page_size=8, max_batch=4)
    b = rp.replay_sim(doc, num_pages=33, page_size=8, max_batch=4)
    ident = rp.identity(a, b)
    assert ident["identical"] is True
    assert ident["determinism_frac"] == 1.0
    assert ident["n_requests"] == 8
    assert a["completed"] == 8 and a["terminals"] == {"result": 8}


def test_identity_flags_a_divergent_request():
    doc = wl.synthetic_workload(8, seed=0, qps=0.5)
    a = rp.replay_sim(doc, num_pages=33, page_size=8, max_batch=4)
    b = json.loads(json.dumps(a))
    b["per_request"][3]["tokens"] = (b["per_request"][3]["tokens"] or 0) + 1
    ident = rp.identity(a, b)
    assert ident["identical"] is False
    assert ident["determinism_frac"] == pytest.approx(7 / 8)
    assert ident["mismatches"][0]["rid"] == a["per_request"][3]["rid"]
    with pytest.raises(ValueError):
        rp.identity(a, {"workload_id": "wl-other", "per_request": []})


def test_replay_sim_recapture_roundtrips_to_same_workload(tmp_path):
    """THE idempotence hook: replaying a workload under a recorder and
    re-capturing the emitted span stream yields the SAME workload id
    (fingerprints pass through verbatim; arrival offsets survive the
    ticks-as-seconds clock at speed 1)."""
    doc = wl.synthetic_workload(6, seed=3, qps=0.5, mean_prompt=16,
                                mean_new=6)
    d = str(tmp_path / "sim")
    rec = rp.replay_recorder(d, doc["workload_id"])
    rp.replay_sim(doc, num_pages=33, page_size=8, max_batch=4,
                  recorder=rec)
    rec.close()
    doc2 = wl.capture(d)
    assert doc2["workload_id"] == doc["workload_id"]
    assert doc2["n_requests"] == doc["n_requests"]
    for r, r2 in zip(doc["requests"], doc2["requests"]):
        assert r2["prompt_len"] == r["prompt_len"]
        assert r2["max_new_tokens"] == r["max_new_tokens"]
        assert r2["fingerprint"] == r["fingerprint"]
    # every replayed span self-labels with its source workload
    from distributed_tensorflow_example_tpu.obs import spans as spans_lib
    rows = spans_lib.load_spans(d)
    assert rows and all(
        row.get("replay_of") == doc["workload_id"] for row in rows)


# ---------------------------------------------------------------- capacity


def _flat_workload(n, arrival_gap_s, max_new):
    reqs = [{"rid": i, "arrival_s": i * arrival_gap_s, "prompt_len": 8,
             "max_new_tokens": max_new} for i in range(n)]
    return {"workload_id": "wl-fixture", "n_requests": n,
            "duration_s": (n - 1) * arrival_gap_s or 1.0,
            "requests": reqs}


def test_forecast_closed_form_exact():
    # 4 requests over 2 s => offered 2 QPS; 10 new tokens each at
    # 100 tok/s => sustainable 10 QPS at util 1.0 — exact by hand
    doc = _flat_workload(4, arrival_gap_s=2 / 3, max_new=10)
    doc["duration_s"] = 2.0
    fc = cap_lib.forecast(doc, 100.0, utilization_target=1.0)
    assert fc["sustainable_qps"] == 10.0
    assert fc["offered_qps"] == 2.0
    assert fc["mean_new_tokens"] == 10.0
    assert fc["required_replicas"] == 1
    assert fc["utilization"] == pytest.approx(0.2)
    # halve the budget: sustainable halves, replicas re-ceil
    fc2 = cap_lib.forecast(doc, 100.0, utilization_target=0.5)
    assert fc2["sustainable_qps"] == 5.0
    assert fc2["required_replicas"] == 1
    fc3 = cap_lib.forecast(doc, 12.0, utilization_target=0.5)
    assert fc3["sustainable_qps"] == 0.6
    assert fc3["required_replicas"] == 4  # ceil(2 / 0.6)
    with pytest.raises(ValueError):
        cap_lib.forecast(doc, 0.0)
    with pytest.raises(ValueError):
        cap_lib.forecast(doc, 10.0, utilization_target=1.5)


def test_measured_knee_prefers_sustained_points():
    points = [
        {"speed": 1, "qps_offered": 1.0, "qps_completed": 1.0,
         "n_requests": 8, "completed": 8},
        {"speed": 2, "qps_offered": 2.0, "qps_completed": 2.0,
         "n_requests": 8, "completed": 8},
        {"speed": 4, "qps_offered": 4.0, "qps_completed": 2.5,
         "n_requests": 8, "completed": 8},
    ]
    knee = cap_lib.measured_knee(points)
    assert knee["measured_qps"] == 2.5
    assert knee["knee_speed"] == 4.0
    assert knee["saturated"] is False
    # past the knee requests start dropping: the unsustained point is
    # excluded from the measurement but flips the saturated bit
    points.append({"speed": 8, "qps_offered": 8.0, "qps_completed": 3.0,
                   "n_requests": 8, "completed": 7})
    knee2 = cap_lib.measured_knee(points)
    assert knee2["measured_qps"] == 2.5
    assert knee2["saturated"] is True
    with pytest.raises(ValueError):
        cap_lib.measured_knee([])


def test_verdict_shortfall_vs_headroom():
    ok = cap_lib.verdict(10.0, 9.0)
    assert ok["ok"] is True and ok["rel_err"] == pytest.approx(0.1)
    short = cap_lib.verdict(10.0, 7.0)      # 30% short > 25% tolerance
    assert short["ok"] is False
    # beating the forecast is headroom (ok), but still counts toward
    # rel_err — a wildly conservative model drifts the gate
    head = cap_lib.verdict(10.0, 14.0)
    assert head["ok"] is True and head["rel_err"] == pytest.approx(0.4)
    with pytest.raises(ValueError):
        cap_lib.verdict(0.0, 1.0)


# ---------------------------------------------------------------- CLI


def test_cli_capture_and_capacity_exit_codes(tmp_path, capsys):
    doc = wl.synthetic_workload(6, seed=0, qps=0.5)
    d = str(tmp_path / "run")
    rec = rp.replay_recorder(d, doc["workload_id"])
    rp.replay_sim(doc, num_pages=33, page_size=8, max_batch=4,
                  recorder=rec)
    rec.close()

    out = str(tmp_path / "cap.json")
    assert obs_cli.main(["capture", d, "-o", out]) == 0
    captured = wl.load_workload(out)
    assert captured["workload_id"] == doc["workload_id"]
    # bad input: 2
    assert obs_cli.main(["capture", str(tmp_path / "nope"), "-o",
                         str(tmp_path / "x.json")]) == 2
    capsys.readouterr()

    # capacity: 0 clean / 2 bad input / 3 measured short of forecast
    assert obs_cli.main(["capacity", out, "--service-tok-s", "100"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["kind"] == "capacity"
    assert rep["workload_id"] == doc["workload_id"]
    assert obs_cli.main(["capacity", str(tmp_path / "nope.json"),
                         "--service-tok-s", "100"]) == 2
    assert obs_cli.main(["capacity", out, "--service-tok-s", "0"]) == 2
    capsys.readouterr()
    fc = cap_lib.forecast(captured, 100.0)
    low = fc["sustainable_qps"] * (1 - cap_lib.DEFAULT_TOLERANCE) - 0.01
    assert obs_cli.main(["capacity", out, "--service-tok-s", "100",
                         "--measured-qps", str(low)]) == 3
    assert obs_cli.main(["capacity", out, "--service-tok-s", "100",
                         "--measured-qps",
                         str(fc["sustainable_qps"])]) == 0
    capsys.readouterr()


# --- the real decode engine (CPU jax; serving imports fine even where
# the training stack's jax API is too new for the container) ---------------


def test_engine_two_replay_identity_and_exactly_once(tmp_path):
    """The acceptance invariant: capture a seeded source run off its
    span stream, replay it TWICE through fresh seeded engines, and the
    two replays agree on every typed terminal and every token count —
    with the collector's exactly-once join holding over each replay's
    self-labeled (replay_of) span dir."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from distributed_tensorflow_example_tpu.models import (
        transformer as tfm)
    from distributed_tensorflow_example_tpu.obs import (
        collector as collector_lib)
    from distributed_tensorflow_example_tpu.obs.spans import SpanRecorder
    from distributed_tensorflow_example_tpu.serving.engine import (
        DecodeEngine)

    spec = tfm.TransformerSpec(
        input_size=64, num_classes=10, seq_len=64, d_model=32,
        n_heads=2, num_blocks=2, d_ff=64, objective="lm",
        vocab_size=64, causal=True, compute_dtype=jnp.bfloat16)
    params = tfm.init(jax.random.PRNGKey(0), spec)

    def settle(eng):
        import time
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0:
            if not eng.sched.live and not eng.sched.waiting:
                time.sleep(0.05)
                break
            time.sleep(0.02)

    # ---- seeded source run
    src = str(tmp_path / "src")
    rec = SpanRecorder(src)
    eng = DecodeEngine(spec, params, page_size=8, max_batch=4, seed=0,
                       recorder=rec)
    eng.start()
    rng = np.random.RandomState(0)
    rids = [eng.submit(rng.randint(1, 64, size=int(n)).tolist(),
                       int(m))
            for n, m in [(5, 4), (9, 3), (7, 5), (12, 4)]]
    results = [eng.result(r, timeout=120.0) for r in rids]
    settle(eng)
    eng.stop()
    rec.close()
    assert all(r is not None for r in results)

    doc = wl.capture(src)
    assert schema_lib.validate_workload(doc) == []
    assert doc["n_requests"] == 4

    # ---- two replays through FRESH engines
    reports = []
    for i in range(2):
        d = str(tmp_path / f"replay{i}")
        rrec = rp.replay_recorder(d, doc["workload_id"])
        e2 = DecodeEngine(spec, params, page_size=8, max_batch=4,
                          seed=0, recorder=rrec)
        e2.start()
        try:
            reports.append(rp.replay_engine(
                e2, doc, vocab_size=64, speed=25.0))
        finally:
            settle(e2)
            e2.stop()
            rrec.close()
        fr = collector_lib.fleet_report([d])
        assert fr["exactly_once"] is True

    ident = rp.identity(reports[0], reports[1])
    assert ident["identical"] is True
    assert ident["determinism_frac"] == 1.0
    assert reports[0]["completed"] == 4
    # token content actually decoded (not just counted): token_sig is
    # present and equal per request
    sigs = {r["rid"]: r["token_sig"] for r in reports[0]["per_request"]}
    assert all(s for s in sigs.values())
    for r in reports[1]["per_request"]:
        assert sigs[r["rid"]] == r["token_sig"]
