"""Optimizer math tests (SURVEY.md §4: SGD update math; Adam parity
with the TF formulation)."""

import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_example_tpu.train import optim


def _tree(vals):
    return {k: jnp.asarray(v, jnp.float32) for k, v in vals.items()}


def test_sgd_update():
    """p <- p - lr*g: GradientDescentOptimizer semantics (example.py:101)."""
    opt = optim.sgd(0.5)
    params = _tree({"w": [1.0, 2.0]})
    grads = _tree({"w": [0.2, -0.4]})
    s = opt.init(params)
    new_p, s = opt.update(grads, s, params)
    np.testing.assert_allclose(np.asarray(new_p["w"]), [0.9, 2.2], rtol=1e-6)


def test_momentum_update():
    opt = optim.momentum(0.1, beta=0.5)
    params = _tree({"w": [0.0]})
    g = _tree({"w": [1.0]})
    s = opt.init(params)
    p, s = opt.update(g, s, params)       # m=1,   p=-0.1
    p, s = opt.update(g, s, p)            # m=1.5, p=-0.25
    np.testing.assert_allclose(np.asarray(p["w"]), [-0.25], rtol=1e-6)


def test_adam_matches_numpy_reference():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    opt = optim.adam(lr, b1, b2, eps)
    rng = np.random.RandomState(0)
    p_np = rng.randn(5).astype(np.float32)
    params = {"w": jnp.asarray(p_np)}
    s = opt.init(params)
    m = np.zeros(5); v = np.zeros(5)
    for t in range(1, 4):
        g_np = rng.randn(5).astype(np.float32)
        params, s = opt.update({"w": jnp.asarray(g_np)}, s, params)
        m = b1 * m + (1 - b1) * g_np
        v = b2 * v + (1 - b2) * g_np**2
        lr_t = lr * np.sqrt(1 - b2**t) / (1 - b1**t)
        p_np = p_np - lr_t * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(np.asarray(params["w"]), p_np, rtol=1e-5, atol=1e-6)


def test_state_pspecs_structure():
    from jax.sharding import PartitionSpec as P

    pp = {"W1": P(None, "model"), "b1": P("model")}
    assert optim.sgd(0.1).state_pspecs(pp) == ()
    assert optim.momentum(0.1).state_pspecs(pp) == {"m": pp}
    adam_specs = optim.adam(0.1).state_pspecs(pp)
    assert adam_specs["count"] == P()
    assert adam_specs["mu"] == pp and adam_specs["nu"] == pp
