"""Optimizer math tests (SURVEY.md §4: SGD update math; Adam parity
with the TF formulation)."""

import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_example_tpu.train import optim


def _tree(vals):
    return {k: jnp.asarray(v, jnp.float32) for k, v in vals.items()}


def test_sgd_update():
    """p <- p - lr*g: GradientDescentOptimizer semantics (example.py:101)."""
    opt = optim.sgd(0.5)
    params = _tree({"w": [1.0, 2.0]})
    grads = _tree({"w": [0.2, -0.4]})
    s = opt.init(params)
    new_p, s = opt.update(grads, s, params)
    np.testing.assert_allclose(np.asarray(new_p["w"]), [0.9, 2.2], rtol=1e-6)


def test_momentum_update():
    opt = optim.momentum(0.1, beta=0.5)
    params = _tree({"w": [0.0]})
    g = _tree({"w": [1.0]})
    s = opt.init(params)
    p, s = opt.update(g, s, params)       # m=1,   p=-0.1
    p, s = opt.update(g, s, p)            # m=1.5, p=-0.25
    np.testing.assert_allclose(np.asarray(p["w"]), [-0.25], rtol=1e-6)


def test_adam_matches_numpy_reference():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    opt = optim.adam(lr, b1, b2, eps)
    rng = np.random.RandomState(0)
    p_np = rng.randn(5).astype(np.float32)
    params = {"w": jnp.asarray(p_np)}
    s = opt.init(params)
    m = np.zeros(5); v = np.zeros(5)
    for t in range(1, 4):
        g_np = rng.randn(5).astype(np.float32)
        params, s = opt.update({"w": jnp.asarray(g_np)}, s, params)
        m = b1 * m + (1 - b1) * g_np
        v = b2 * v + (1 - b2) * g_np**2
        lr_t = lr * np.sqrt(1 - b2**t) / (1 - b1**t)
        p_np = p_np - lr_t * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(np.asarray(params["w"]), p_np, rtol=1e-5, atol=1e-6)


def test_adam_bf16_moments_match_numpy_oracle():
    """bf16 moment storage (r5, VERDICT r4 next #9): the update math
    stays f32 — slots cast up on read, the fresh f32 moment drives the
    param step, only the STORE rounds — so a numpy oracle that rounds
    its f32 moments through bfloat16 at exactly the store boundary
    reproduces the params EXACTLY (not approximately) over multiple
    steps, with f32 master params throughout. The MOMENTS match the
    oracle bit-for-bit (the rounding contract); the params carry the
    same fp-associativity tolerance as the f32 Adam oracle (XLA fuses
    the update arithmetic)."""
    import ml_dtypes

    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    opt = optim.adam(lr, b1, b2, eps, moments_dtype=jnp.bfloat16)
    rng = np.random.RandomState(3)
    p_np = rng.randn(64).astype(np.float32)
    params = {"w": jnp.asarray(p_np)}
    s = opt.init(params)
    assert s["mu"]["w"].dtype == jnp.bfloat16
    assert s["nu"]["w"].dtype == jnp.bfloat16
    m = np.zeros(64, np.float32)
    v = np.zeros(64, np.float32)
    for t in range(1, 6):
        g_np = rng.randn(64).astype(np.float32)
        params, s = opt.update({"w": jnp.asarray(g_np)}, s, params)
        # oracle: f32 math on the bf16-rounded PREVIOUS moments
        m_f = b1 * m.astype(np.float32) + (1 - b1) * g_np
        v_f = b2 * v.astype(np.float32) + (1 - b2) * g_np**2
        lr_t = lr * np.sqrt(1 - b2**t) / (1 - b1**t)
        p_np = (p_np - lr_t * m_f / (np.sqrt(v_f) + eps)).astype(
            np.float32)
        m = m_f.astype(ml_dtypes.bfloat16).astype(np.float32)
        v = v_f.astype(ml_dtypes.bfloat16).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(s["mu"]["w"]).astype(np.float32), m)
    assert params["w"].dtype == jnp.float32          # f32 master
    np.testing.assert_allclose(np.asarray(params["w"]), p_np,
                               rtol=1e-5, atol=1e-6)
    # the rounding is benign: close to the exact-f32 trajectory
    opt32 = optim.adam(lr, b1, b2, eps)
    rng = np.random.RandomState(3)
    p32 = {"w": jnp.asarray(rng.randn(64).astype(np.float32))}
    s32 = opt32.init(p32)
    for _ in range(5):
        g = rng.randn(64).astype(np.float32)
        p32, s32 = opt32.update({"w": jnp.asarray(g)}, s32, p32)
    np.testing.assert_allclose(np.asarray(params["w"]),
                               np.asarray(p32["w"]), rtol=2e-3,
                               atol=2e-4)


def test_state_pspecs_structure():
    from jax.sharding import PartitionSpec as P

    pp = {"W1": P(None, "model"), "b1": P("model")}
    assert optim.sgd(0.1).state_pspecs(pp) == ()
    assert optim.momentum(0.1).state_pspecs(pp) == {"m": pp}
    adam_specs = optim.adam(0.1).state_pspecs(pp)
    assert adam_specs["count"] == P()
    assert adam_specs["mu"] == pp and adam_specs["nu"] == pp
