"""Fast-path (scanned) local-SGD: the async analog as one device
program (parallel/epoch.py:build_local_run_to_completion)."""

import jax
import numpy as np

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models.mlp import MLPSpec
from distributed_tensorflow_example_tpu.parallel import epoch as epoch_lib
from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
from distributed_tensorflow_example_tpu.parallel import step as step_lib
from distributed_tensorflow_example_tpu.train.optim import make_optimizer
from distributed_tensorflow_example_tpu.train.state import create_train_state

SPEC = MLPSpec(input_size=16, hidden_sizes=(8,), num_classes=4)


def _setup(sync_period, spe, epochs, dp=8):
    cfg = Config(learning_rate=0.2, sync_period=sync_period)
    mesh = mesh_lib.build_mesh(dp, 1)
    opt = make_optimizer(cfg)
    state = step_lib.stack_state(create_train_state(jax.random.PRNGKey(1), SPEC, opt), dp)
    state = mesh_lib.place_state(state, mesh, step_lib._stacked_specs(state))
    runner = epoch_lib.build_local_run_to_completion(cfg, mesh, SPEC, opt, spe, epochs)(state)
    rng = np.random.RandomState(0)
    n = dp * spe * 4  # local batch 4
    imgs = rng.rand(n, SPEC.input_size).astype(np.float32)
    lbls = np.eye(SPEC.num_classes, dtype=np.float32)[rng.randint(0, 4, n)]
    img_d, lbl_d, spe2 = epoch_lib.shard_dataset(mesh, imgs, lbls, dp * 4)
    assert spe2 == spe
    return state, runner, img_d, lbl_d


def test_synced_at_period_boundary(devices8):
    """After K steps (K = sync_period), every shard holds the averaged
    params — the reconciliation fired on the last scan step."""
    K = 5
    state, runner, img_d, lbl_d = _setup(sync_period=K, spe=K, epochs=1)
    state, costs, accs = runner(state, img_d, lbl_d, jax.random.PRNGKey(3))
    w = np.asarray(jax.device_get(state.params["W1"]))  # [dp, in, hid]
    np.testing.assert_allclose(w, np.broadcast_to(w[0], w.shape), rtol=1e-6)
    assert int(state.step) == K
    assert np.isfinite(costs).all()


def test_diverged_between_syncs(devices8):
    """One step past the boundary, shards have drifted apart again."""
    K = 5
    state, runner, img_d, lbl_d = _setup(sync_period=K, spe=K + 1, epochs=1)
    state, costs, accs = runner(state, img_d, lbl_d, jax.random.PRNGKey(3))
    w = np.asarray(jax.device_get(state.params["W1"]))
    assert np.abs(w - w[0:1]).max() > 1e-7


def test_learns(devices8):
    state, runner, img_d, lbl_d = _setup(sync_period=4, spe=20, epochs=5)
    state, costs, accs = runner(state, img_d, lbl_d, jax.random.PRNGKey(3))
    costs = np.asarray(costs)  # [epochs, spe]
    assert costs[-1].mean() < costs[0].mean()


def test_fast_runner_tp_equals_single_device(devices8):
    """The whole-run scan program under a (4,2) dp x tp mesh matches the
    (4,1) pure-DP program step for step (Megatron split changes nothing
    numerically on the fast path either)."""
    def go(dp, mp):
        cfg = Config(learning_rate=0.2, model_parallel=mp)
        mesh = mesh_lib.build_mesh(dp, mp)
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), SPEC, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(SPEC, opt, mp)
        )
        runner = epoch_lib.build_run_to_completion(cfg, mesh, SPEC, opt, 6, 2)
        rng = np.random.RandomState(0)
        n = 8 * 6 * 4
        imgs = (rng.randint(0, 256, size=(n, SPEC.input_size)) / 255.0).astype(
            np.float32
        )
        lbls = np.eye(SPEC.num_classes, dtype=np.float32)[
            rng.randint(0, 4, n)
        ]
        img_d, lbl_d, spe = epoch_lib.shard_dataset(mesh, imgs, lbls, 8 * 4)
        assert spe == 6
        state, costs, _ = runner(state, img_d, lbl_d, jax.random.PRNGKey(3))
        return jax.device_get(state.params), np.asarray(costs)

    # same dp on both meshes so the data sharding (and thus the
    # trajectory) is identical; only the model axis differs
    p_tp, c_tp = go(4, 2)
    p_dp4, c_dp4 = go(4, 1)
    np.testing.assert_allclose(c_tp, c_dp4, rtol=1e-5, atol=1e-6)
    for k in p_dp4:
        np.testing.assert_allclose(p_tp[k], p_dp4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_remat_numerically_inert(devices8):
    """--remat threads into the scanned local-SGD runner's loss and
    changes nothing numerically (recompute == stored activations)."""

    def go(remat):
        cfg = Config(learning_rate=0.2, sync_period=3, remat=remat)
        mesh = mesh_lib.build_mesh(8, 1)
        opt = make_optimizer(cfg)
        state = step_lib.stack_state(
            create_train_state(jax.random.PRNGKey(1), SPEC, opt), 8
        )
        state = mesh_lib.place_state(state, mesh, step_lib._stacked_specs(state))
        runner = epoch_lib.build_local_run_to_completion(
            cfg, mesh, SPEC, opt, 6, 1
        )(state)
        rng = np.random.RandomState(0)
        n = 8 * 6 * 4
        imgs = rng.rand(n, SPEC.input_size).astype(np.float32)
        lbls = np.eye(SPEC.num_classes, dtype=np.float32)[rng.randint(0, 4, n)]
        img_d, lbl_d, _ = epoch_lib.shard_dataset(mesh, imgs, lbls, 8 * 4)
        state, _, _ = runner(state, img_d, lbl_d, jax.random.PRNGKey(3))
        return np.asarray(jax.device_get(state.params["W1"]))

    np.testing.assert_array_equal(go(False), go(True))
