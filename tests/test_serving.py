"""Decode engine v2 (ISSUE 9): paged KV cache + continuous batching.

Two halves, mirroring the serving stack's own split:

- **scheduler** (pure Python, no jax anywhere in the process): block
  allocator discipline, admission/retirement ordering, the
  no-recompile bucket invariant, and the continuous-vs-static tick
  accounting the bench gates on;
- **engine/kv_cache** (CPU jax): paged==contiguous greedy bit-parity
  across page sizes — including ragged lengths and mid-flight
  admission churn — prefill-vs-stepwise consistency, fused sampling,
  the donated contiguous step, the stats schema, and the ``/generate``
  HTTP front door.

The TP-sharded cache parity test rides the mesh and skips on
environments whose jax predates the repo's API (the PR-5/7 precedent).
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from conftest import needs_stack  # noqa: E402

from distributed_tensorflow_example_tpu.serving import (  # noqa: E402
    scheduler as sl,
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from distributed_tensorflow_example_tpu.models import (  # noqa: E402
    transformer as tfm,
)
from distributed_tensorflow_example_tpu.serving import (  # noqa: E402
    kv_cache as kvc,
)
from distributed_tensorflow_example_tpu.serving.engine import (  # noqa: E402
    DecodeEngine,
)


# --- pure-Python scheduler -----------------------------------------------


def test_scheduler_import_is_pure_python():
    """The scheduler (and the package __init__ resolving it) imports
    with NO jax in the process — what keeps the tier-1 scheduler tests
    and bench_serving's analytic half runnable everywhere."""
    code = (
        "import sys\n"
        "from distributed_tensorflow_example_tpu.serving import "
        "scheduler as sl\n"
        "from distributed_tensorflow_example_tpu import serving\n"
        "r = sl.simulate(serving.ContinuousScheduler(9, 4, 2),"
        " [(0, 3, 2), (1, 5, 4)])\n"
        "assert r.decode_ticks > 0\n"
        "assert 'jax' not in sys.modules, 'scheduler pulled in jax'\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True, cwd=_REPO)


def test_shape_buckets_ladder():
    assert sl.shape_buckets(1) == (1,)
    assert sl.shape_buckets(8) == (1, 2, 4, 8)
    assert sl.shape_buckets(6) == (1, 2, 4, 6)       # cap always present
    assert sl.shape_buckets(8, floor=2) == (2, 4, 8)
    with pytest.raises(ValueError):
        sl.shape_buckets(0)


def test_bucket_for_picks_smallest_cover():
    buckets = sl.shape_buckets(8)
    assert sl.bucket_for(1, buckets) == 1
    assert sl.bucket_for(3, buckets) == 4
    assert sl.bucket_for(8, buckets) == 8
    with pytest.raises(ValueError):
        sl.bucket_for(9, buckets)


def test_block_allocator_discipline():
    a = sl.BlockAllocator(num_pages=6, page_size=4)
    assert a.usable == 5 and a.free_count == 5 and a.in_use == 0
    got = a.alloc(3)
    assert got is not None and len(got) == 3
    assert all(sl.SCRATCH_PAGE < p < 6 for p in got)   # scratch reserved
    assert a.in_use == 3
    # all-or-nothing: a partial grant would deadlock admission
    assert a.alloc(3) is None
    assert a.free_count == 2                           # nothing leaked
    a.free(got)
    assert a.free_count == 5
    # LIFO reuse keeps hot pages hot
    assert a.alloc(1) == [got[0]]
    with pytest.raises(ValueError):                    # double free
        a.free([got[0], got[0]])
    with pytest.raises(ValueError):                    # outside the pool
        a.free([sl.SCRATCH_PAGE])
    with pytest.raises(ValueError):
        sl.BlockAllocator(num_pages=1, page_size=4)
    with pytest.raises(ValueError):
        sl.BlockAllocator(num_pages=4, page_size=0)


def test_submit_validation():
    s = sl.ContinuousScheduler(num_pages=5, page_size=4, max_batch=2)
    with pytest.raises(ValueError):
        s.submit(0, prompt_len=0, max_new_tokens=1)
    with pytest.raises(ValueError):
        s.submit(0, prompt_len=4, max_new_tokens=0)
    with pytest.raises(ValueError):                    # pool can't ever fit
        s.submit(0, prompt_len=30, max_new_tokens=4)
    with pytest.raises(ValueError):
        sl.ContinuousScheduler(5, 4, max_batch=0)


def test_retirement_frees_pages_before_admission():
    """A finishing sequence's pages return at the NEXT tick boundary
    BEFORE that tick's admissions, so a waiter blocked on pages is
    admitted the very tick the pages free."""
    # pool: 4 usable pages; each request needs ceil((4+4-1)/4)=2 pages
    s = sl.ContinuousScheduler(num_pages=5, page_size=4, max_batch=4)
    s.submit(0, 4, 4)
    s.submit(1, 4, 4)
    s.submit(2, 4, 4)                     # blocked: 0 pages left
    plan = s.plan_tick()
    assert plan.prefills == (0, 1)
    assert s.alloc.free_count == 0
    assert [x.rid for x in s.waiting] == [2]
    # run 0 to completion, keep 1 alive
    s.record_prefill(0)
    s.record_prefill(1)
    s.record_decode([0, 1])
    s.record_decode([0, 1])
    s.record_decode([0])                  # rid 0 done (4 tokens)
    assert s._seq(0).done and not s._seq(1).done
    plan = s.plan_tick()                  # retire 0 -> admit 2, same tick
    assert plan.prefills == (2,)
    assert 0 not in plan.decodes and 0 in s.finished
    assert sorted(plan.decodes) == [1, 2]


def test_fifo_head_of_line_blocks_admission():
    """When the FIFO head cannot get its pages, later (smaller)
    requests must NOT jump it — admission stops so the head cannot
    starve forever."""
    s = sl.ContinuousScheduler(num_pages=5, page_size=4, max_batch=4)
    s.submit(0, 4, 4)                     # takes 2 of 4 pages
    s.submit(1, 8, 5)                     # needs 3: blocked
    s.submit(2, 2, 2)                     # would fit (1 page) but waits
    plan = s.plan_tick()
    assert plan.prefills == (0,)
    assert [x.rid for x in s.waiting] == [1, 2]


def test_arrival_gating():
    s = sl.ContinuousScheduler(num_pages=9, page_size=4, max_batch=4)
    s.submit(0, 4, 2, arrival=0.0)
    s.submit(1, 4, 2, arrival=5.0)
    plan = s.plan_tick(now=1.0)
    assert plan.prefills == (0,)          # rid 1 hasn't arrived
    plan = s.plan_tick(now=5.0)
    assert plan.prefills == (1,)


def test_no_recompile_bucket_invariant():
    """Every TickPlan shape the scheduler can emit comes from the
    finite precomputed (batch bucket, page-width bucket) set — the
    invariant that keeps membership churn from ever recompiling."""
    s = sl.ContinuousScheduler(num_pages=17, page_size=4, max_batch=6)
    rng = np.random.RandomState(0)
    reqs = [(i, int(rng.randint(1, 12)), int(rng.randint(1, 9)),
             float(i) * 0.7) for i in range(40)]
    res = sl.simulate(s, reqs)
    allowed = {(b, w) for b in s.batch_buckets
               for w in s.kv_page_buckets}
    assert set(res.shapes) <= allowed
    # the ladder is tiny against the raw (batch x width) churn
    assert len(res.shapes) <= len(s.batch_buckets) * 4
    assert set(res.finish_ticks) == {r[0] for r in reqs}  # all served


def test_continuous_strictly_beats_static_on_ragged():
    """THE acceptance invariant (deterministic, every backend):
    continuous batching backfills retired slots the tick they free, so
    on ragged lengths with more requests than slots it finishes the
    same request set in strictly fewer decode ticks than the static
    baseline."""
    rng = np.random.RandomState(3)
    reqs = [(i, int(rng.randint(2, 20)), int(rng.randint(2, 16)))
            for i in range(24)]
    cont = sl.simulate(sl.ContinuousScheduler(33, 4, 4), reqs)
    stat = sl.simulate(sl.StaticBatchScheduler(33, 4, 4), reqs)
    assert set(cont.finish_ticks) == set(stat.finish_ticks)
    assert cont.decode_ticks < stat.decode_ticks
    assert 0.0 < cont.occupancy <= 1.0
    # and the per-request latencies are well-formed
    assert all(v > 0 for v in cont.latency_ticks.values())


def test_page_filling_prompt_with_one_new_token():
    """A max_new_tokens=1 request whose prompt fills its last reserved
    page must plan cleanly: the prefill finishes WITHOUT a same-tick
    decode, so plan_tick projects no extra row — the old +1 pushed the
    width past the reservation (and past the kv_page_buckets ladder
    when the pool is exactly one sequence wide), crashing plan_tick
    for a validly admitted request."""
    for scheduler_cls in (sl.ContinuousScheduler,
                          sl.StaticBatchScheduler):
        s = scheduler_cls(num_pages=2, page_size=4, max_batch=1)
        s.submit(0, prompt_len=4, max_new_tokens=1)
        plan = s.plan_tick()
        assert plan.prefills == (0,)
        assert plan.kv_pages == 1            # within the 1-page ladder
        s.record_prefill(0)
        assert s._seq(0).done                # finished by the prefill
    # a >1 max_new request still projects the same-tick decode row
    s2 = sl.ContinuousScheduler(num_pages=3, page_size=4, max_batch=1)
    s2.submit(1, prompt_len=4, max_new_tokens=2)
    plan2 = s2.plan_tick()
    assert plan2.kv_pages == 2               # rows = prompt + 1


def test_uniform_single_group_policies_tie():
    """With one group of uniform requests there is nothing to
    backfill: both policies must plan the identical tick count (the
    continuous win is ragged-lengths churn, not magic)."""
    reqs = [(i, 4, 6) for i in range(4)]
    cont = sl.simulate(sl.ContinuousScheduler(17, 4, 4), reqs)
    stat = sl.simulate(sl.StaticBatchScheduler(17, 4, 4), reqs)
    assert cont.decode_ticks == stat.decode_ticks


def test_static_holds_slots_until_group_retires():
    """The static baseline keeps finished members' slots (its defining
    waste): the batch bucket stays at the group size while stragglers
    run, and no admission happens mid-group."""
    s = sl.StaticBatchScheduler(num_pages=17, page_size=4, max_batch=2)
    s.submit(0, 2, 2)
    s.submit(1, 2, 6)
    s.submit(2, 2, 2)
    plan = s.plan_tick()
    assert plan.prefills == (0, 1)
    s.record_prefill(0)
    s.record_prefill(1)
    while True:
        plan = s.plan_tick()
        if plan is None or 0 in s.finished and 1 in s.finished:
            break
        # rid 2 never joins mid-group, the bucket stays group-sized
        assert plan.prefills == ()
        assert plan.batch_bucket == 2
        s.record_decode(list(plan.decodes))
    assert 2 not in s.finished


# --- kv_cache / engine (CPU jax) -----------------------------------------


def _spec(**kw):
    base = dict(input_size=32, num_classes=10, seq_len=32, d_model=32,
                n_heads=2, num_blocks=2, d_ff=64, objective="lm",
                vocab_size=50, causal=True)
    base.update(kw)
    return tfm.TransformerSpec(**base)


@pytest.fixture(scope="module")
def lm():
    spec = _spec()
    return spec, tfm.init(jax.random.PRNGKey(0), spec)


@pytest.mark.parametrize("page_size", [4, 8, 16])
def test_paged_engine_matches_contiguous_generate(lm, page_size):
    """THE parity acceptance test: greedy decode through the full
    serving stack (prefill -> paged cache -> continuous-batching
    decode with fused sampling) is token-identical to the contiguous
    ``generate`` path, across page sizes, ragged prompt lengths, and
    mid-flight admission churn (6 requests through 3 slots)."""
    spec, params = lm
    rng = np.random.RandomState(1)
    lens = (3, 7, 5, 11, 2, 8)
    prompts = [rng.randint(0, 50, size=n).tolist() for n in lens]
    n_new = 6
    refs = []
    for p in prompts:
        out = tfm.generate(spec, params, jnp.asarray([p], jnp.int32))
        refs.append(np.asarray(out)[0, len(p):len(p) + n_new].tolist())
    eng = DecodeEngine(spec, params, page_size=page_size, max_batch=3)
    rids = [eng.submit(p, n_new) for p in prompts]
    ticks = eng.run_until_idle()
    assert ticks > 0
    for rid, ref, p in zip(rids, refs, prompts):
        res = eng.result(rid, timeout=10.0)
        assert res is not None
        assert res["tokens"] == ref
        assert res["prompt"] == p
        assert res["latency_ms"] >= res["ttft_ms"] >= 0.0


def test_paged_decode_step_bit_parity(lm):
    """paged_decode_step == contiguous decode_step BITWISE on the
    same batch (the two paths share ``_decode_forward``; only the
    cache adapter differs), chained over several positions and both
    page sizes straddling the position count."""
    spec, params = lm
    b, steps = 3, 9
    rng = np.random.RandomState(2)
    toks = rng.randint(0, 50, size=(steps, b)).astype(np.int32)
    for page_size in (4, 16):
        dense = tfm.init_decode_cache(spec, b)
        npages = 1 + b * (steps // page_size + 1)
        paged = kvc.init_paged_cache(spec, npages, page_size)
        # per-sequence page chains: seq i owns pages i*k+1 ...
        per = steps // page_size + 1
        bt = jnp.asarray([[1 + i * per + j for j in range(per)]
                          for i in range(b)], jnp.int32)
        for pos in range(steps):
            ld, dense = tfm.decode_step(spec, params, dense,
                                        jnp.asarray(toks[pos]), pos)
            lp, paged = kvc.paged_decode_step(
                spec, params, paged, bt, jnp.asarray(toks[pos]),
                jnp.full((b,), pos, jnp.int32))
            np.testing.assert_array_equal(np.asarray(ld),
                                          np.asarray(lp))


def test_paged_decode_ragged_matches_per_sequence(lm):
    """One ragged paged batch (different positions per row) produces
    the same greedy tokens as each sequence decoded alone through the
    contiguous path — the padding-free claim."""
    spec, params = lm
    rng = np.random.RandomState(3)
    page_size, b = 4, 3
    hist = [rng.randint(0, 50, size=n).astype(np.int32)
            for n in (2, 5, 3)]
    # contiguous per-sequence references: feed the history, then the
    # greedy continuation's next token
    want = []
    for h in hist:
        cache = tfm.init_decode_cache(spec, 1)
        for pos, t in enumerate(h):
            logits, cache = tfm.decode_step(
                spec, params, cache, jnp.asarray([t], jnp.int32), pos)
        want.append(int(np.argmax(np.asarray(logits)[0])))
    # paged ragged batch: replay the same histories through one pool
    paged = kvc.init_paged_cache(spec, 10, page_size)
    bt = jnp.asarray([[1, 2], [3, 4], [5, 6]], jnp.int32)
    maxlen = max(len(h) for h in hist)
    got_last = [None] * b
    for step in range(maxlen):
        rows = [i for i in range(b) if step < len(hist[i])]
        tok = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        for i in rows:
            tok[i] = hist[i][step]
            pos[i] = step
        # dead rows re-write their row-0 scratch position; their
        # logits are ignored — the engine's dead-slot convention
        logits, paged = kvc.paged_decode_step(
            spec, params, paged, bt, jnp.asarray(tok),
            jnp.asarray(pos))
        for i in rows:
            if step == len(hist[i]) - 1:
                got_last[i] = int(np.argmax(np.asarray(logits)[i]))
    assert got_last == want


def test_prefill_matches_stepwise_decode(lm):
    """prefill_into_pages (ONE batched forward scattered into pages)
    agrees with token-by-token contiguous decoding of the same
    prompts: same next-token argmax, logits equal to float tolerance
    (batched attention sums in a different order), and the paged rows
    it wrote support bit-identical continuation."""
    spec, params = lm
    rng = np.random.RandomState(4)
    page_size = 4
    lens = (3, 6)
    prompts = [rng.randint(0, 50, size=n).astype(np.int32)
               for n in lens]
    pb = 8
    toks = np.zeros((2, pb), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    paged = kvc.init_paged_cache(spec, 7, page_size)
    bt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    logits, paged = kvc.prefill_into_pages(
        spec, params, paged, bt, jnp.asarray(toks),
        jnp.asarray(lens, jnp.int32))
    for i, p in enumerate(prompts):
        cache = tfm.init_decode_cache(spec, 1)
        for pos, t in enumerate(p):
            ref, cache = tfm.decode_step(
                spec, params, cache, jnp.asarray([t], jnp.int32), pos)
        ref = np.asarray(ref)[0]
        got = np.asarray(logits)[i]
        assert int(np.argmax(got)) == int(np.argmax(ref))
        np.testing.assert_allclose(got, ref, atol=1e-4)


def test_sample_tokens_fused_selection():
    """Greedy rows take the argmax, temperature rows draw from the
    scaled categorical — selected PER ROW in one program, and
    deterministic per key."""
    rng = np.random.RandomState(5)
    logits = jnp.asarray(rng.randn(6, 64).astype(np.float32))
    temp = jnp.asarray([0.0, 0.0, 1.0, 0.7, 0.0, 1.3], jnp.float32)
    key = jax.random.PRNGKey(0)
    out1 = np.asarray(kvc.sample_tokens(logits, key, temp))
    out2 = np.asarray(kvc.sample_tokens(logits, key, temp))
    np.testing.assert_array_equal(out1, out2)
    greedy = np.argmax(np.asarray(logits), axis=-1)
    np.testing.assert_array_equal(out1[temp == 0.0],
                                  greedy[temp == 0.0])
    # all-greedy temperature ignores the key entirely
    out3 = np.asarray(kvc.sample_tokens(
        logits, jax.random.PRNGKey(9), jnp.zeros((6,), jnp.float32)))
    np.testing.assert_array_equal(out3, greedy)
    # a different key re-draws the sampled rows (64-way flat logits:
    # 3 rows all colliding is ~1e-5)
    out4 = np.asarray(kvc.sample_tokens(
        logits, jax.random.PRNGKey(9), temp))
    assert (out4[temp > 0] != out1[temp > 0]).any()


def test_decode_step_fn_matches_decode_step(lm):
    """The donated-buffer compiled step (the no-copy satellite) is
    bit-identical to the plain decode_step, and the lru cache hands
    back the same program for the same (spec, axis, donate)."""
    spec, params = lm
    fn = tfm.decode_step_fn(spec, donate=False)
    assert tfm.decode_step_fn(spec, donate=False) is fn
    # compiled reference WITHOUT donation: the comparison isolates the
    # donation plumbing (eager-vs-jit would differ in fusion noise)
    ref = jax.jit(lambda p, c, t, pos: tfm.decode_step(spec, p, c, t,
                                                       pos))
    cache = tfm.init_decode_cache(spec, 2)
    cache2 = tfm.init_decode_cache(spec, 2)
    rng = np.random.RandomState(6)
    for pos in range(5):
        tok = jnp.asarray(rng.randint(0, 50, size=2), jnp.int32)
        la, cache = ref(params, cache, tok, jnp.asarray(pos))
        lb, cache2 = fn(params, cache2, tok, jnp.asarray(pos))
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for k in cache:
        np.testing.assert_array_equal(np.asarray(cache[k]),
                                      np.asarray(cache2[k]))


def test_engine_stats_contract_and_counters(lm):
    """stats() satisfies obs/schema.SERVING_STATS (what /status and
    the dtx_generate_* gauges export) and its counters add up."""
    from distributed_tensorflow_example_tpu.obs import schema

    spec, params = lm
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2)
    rids = [eng.submit([1, 2, 3], 4, temperature=t)
            for t in (0.0, 0.8, 0.0)]
    eng.run_until_idle()
    for rid in rids:
        assert eng.result(rid, timeout=10.0) is not None
    st = eng.stats()
    assert schema.validate_serving_stats(st) == []
    assert st["requests_total"] == st["completed_total"] == 3
    assert st["inflight"] == st["queued"] == 0
    assert st["tokens_generated_total"] == 3 * 4
    assert st["latency_p99_ms"] >= st["latency_p50_ms"] > 0
    # the r12 satellite: TTFT carries BOTH percentiles (the p99 SLO's
    # data source — /metrics exported p50 only before)
    assert st["ttft_p99_ms"] >= st["ttft_p50_ms"] > 0
    assert st["page_occupancy_frac"] == 0.0      # everything freed
    assert st["prefills_total"] == 3


def test_engine_no_recompile_invariant(lm):
    """Every compiled program the engine built is keyed by a shape
    from the finite bucket ladders — admission/retirement churn can
    re-bucket but never invent a shape."""
    spec, params = lm
    eng = DecodeEngine(spec, params, page_size=4, max_batch=3)
    rng = np.random.RandomState(7)
    rids = [eng.submit(rng.randint(0, 50, size=int(n)).tolist(),
                       int(m))
            for n, m in rng.randint(1, 9, size=(7, 2))]
    eng.run_until_idle()
    for rid in rids:
        assert eng.result(rid, timeout=10.0) is not None
    sched = eng.sched
    for kind, a, b in eng.shapes_used:
        if kind == "decode":
            assert a in sched.batch_buckets
            assert b in sched.kv_page_buckets
        else:
            assert a in eng.prompt_buckets
    decode_shapes = {(a, b) for k, a, b in eng.shapes_used
                     if k == "decode"}
    assert set(eng._decode_fns) == decode_shapes


def test_engine_loop_failure_fails_pending_fast(lm, monkeypatch):
    """A tick raising inside the background loop must not strand the
    server: pending results fail IMMEDIATELY (no 600s timeout against
    a dead worker), new submits are refused, and the failure names
    the original exception."""
    spec, params = lm
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2)
    monkeypatch.setattr(
        eng, "step",
        lambda: (_ for _ in ()).throw(RuntimeError("boom tick")))
    # queue BEFORE starting the doomed loop: with start() first the
    # background thread can die before submit runs, and submit then
    # (correctly) refuses a failed engine — a timing flake, not the
    # pending-request scenario this test pins
    rid = eng.submit([1, 2, 3], 4)
    eng.start()
    res = eng.result(rid, timeout=10.0)
    assert res is not None and "boom tick" in res["error"]
    with pytest.raises(RuntimeError, match="boom tick"):
        eng.submit([1], 1)
    eng.stop()


def test_engine_retention_is_bounded(lm, monkeypatch):
    """Completed-request state is evicted beyond the retention cap
    and per-rid decode state dies at finish, so a long-running server
    does not grow per request forever; counters and the rolling
    latency window keep reporting."""
    import distributed_tensorflow_example_tpu.serving.engine as eng_mod

    monkeypatch.setattr(eng_mod, "RETAIN_FINISHED", 3)
    spec, params = lm
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2)
    rids = [eng.submit([1 + i % 4], 2) for i in range(8)]
    eng.run_until_idle()
    assert len(eng._results) == 3                 # oldest 5 evicted
    assert not eng._temps and not eng._last_tok
    assert not eng.sched.finished
    with pytest.raises(KeyError):
        eng.result(rids[0], timeout=0.1)          # evicted
    assert eng.result(rids[-1], timeout=10.0)["tokens"]
    st = eng.stats()
    assert st["requests_total"] == st["completed_total"] == 8
    assert st["latency_p99_ms"] > 0


def test_engine_rejects_bad_requests(lm):
    spec, params = lm
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2)
    with pytest.raises(ValueError):
        eng.submit([], 4)
    with pytest.raises(ValueError):
        eng.submit([1, 99], 4)                    # outside the vocab
    with pytest.raises(ValueError):
        eng.submit([1] * 30, 8)                   # past max_len
    with pytest.raises(ValueError):
        DecodeEngine(_spec(objective="classify", causal=False),
                     params, page_size=8)
    with pytest.raises(ValueError):
        DecodeEngine(spec, params, max_len=64)    # > seq_len


def test_generate_endpoint_round_trip(lm, tmp_path):
    """POST /generate through the obs StatusServer front door: the
    handler blocks on ITS request while the engine's background loop
    shares decode ticks, /status grows a serving section, /metrics
    the dtx_generate_* gauges, and malformed posts are 400s."""
    from distributed_tensorflow_example_tpu.obs.serve import StatusServer

    spec, params = lm
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2)
    eng.start()
    srv = StatusServer(str(tmp_path), engine=eng)
    port = srv.start(0)
    assert port
    try:
        prompt = [5, 4, 3]
        ref = tfm.generate(spec, params,
                           jnp.asarray([prompt], jnp.int32))
        want = np.asarray(ref)[0, 3:3 + 5].tolist()
        body = json.dumps({"prompt": prompt,
                           "max_new_tokens": 5}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            res = json.loads(r.read())
        assert r.status == 200 if hasattr(r, "status") else True
        assert res["tokens"] == want
        assert res["latency_ms"] >= res["ttft_ms"] >= 0.0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["serving"]["completed_total"] == 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dtx_generate_completed_total 1" in text
        assert "dtx_generate_latency_p99_ms" in text
        # malformed: prompt must be a token-id list
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "hi"}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
    finally:
        srv.close()
        eng.stop()


def test_generate_endpoint_requires_engine(tmp_path):
    """Without an attached engine the POST surface reports 503 (the
    plain training status server shape is unchanged)."""
    from distributed_tensorflow_example_tpu.obs.serve import StatusServer

    srv = StatusServer(str(tmp_path))
    port = srv.start(0)
    assert port
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": [1]}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
    finally:
        srv.close()


# --- request-lifecycle spans (ISSUE 12 tentpole, engine side) -------------


def test_engine_spans_reconstruct_exactly_once(lm, tmp_path):
    """THE spans acceptance: every accepted request in a REAL engine
    run (6 ragged requests through 3 slots, admission churn included)
    is reconstructible exactly-once from spans.<proc>.jsonl — all
    five milestones, engine-side ttft, scheduler-side page/tick
    attribution — and the stream validates against the schema."""
    from distributed_tensorflow_example_tpu.obs import (
        schema as schema_lib,
    )
    from distributed_tensorflow_example_tpu.obs import (
        spans as spans_lib,
    )

    spec, params = lm
    rec = spans_lib.SpanRecorder(str(tmp_path))
    eng = DecodeEngine(spec, params, page_size=4, max_batch=3,
                       recorder=rec)
    rng = np.random.RandomState(11)
    lens = (3, 7, 5, 11, 2, 8)
    n_new = 6
    prompts = [rng.randint(0, 50, size=n).tolist() for n in lens]
    rids = [eng.submit(p, n_new) for p in prompts]
    eng.run_until_idle()
    for rid in rids:
        assert eng.result(rid, timeout=10.0) is not None
    rec.close()
    assert schema_lib.validate_span_file(rec.path) == []
    rows = spans_lib.read_spans(rec.path)
    recs = spans_lib.reconstruct(rows)
    assert set(recs) == {(0, rid) for rid in rids}
    for rid, p in zip(rids, prompts):
        r = recs[(0, rid)]
        assert r["complete"], (rid, r["errors"])
        assert r["prompt_len"] == len(p)
        assert r["generated"] == r["max_new_tokens"] == n_new
        # prefill emits token 1; the rest are shared decode ticks
        assert r["decode_ticks"] == n_new - 1
        assert r["ttft_ms"] > 0
        assert r["latency_ms"] >= r["ttft_ms"]
        for key in ("submit_t", "admit_t", "prefill_t",
                    "first_token_t", "retire_t"):
            assert key in r, (rid, key)
        assert r["pages_held"] >= 1
    # engine counters and the span stream agree
    st = eng.stats()
    assert st["requests_total"] == len(
        [r for r in rows if r["event"] == "submit"])
    assert st["prefills_total"] == len(
        [r for r in rows if r["event"] == "prefill"])
    assert st["decode_ticks_total"] == len(
        [r for r in rows if r["event"] == "tick"])
    # with only 3 slots, somebody was blocked and narrated why
    assert any(r["blocked"] for r in recs.values())


def test_engine_tracing_token_identical(lm, tmp_path):
    """Greedy (and seeded-temperature) outputs are token-identical
    with tracing on vs off — the recorder is host-side appends only,
    never touching the RNG fold-in or the compiled programs."""
    from distributed_tensorflow_example_tpu.obs import (
        spans as spans_lib,
    )

    spec, params = lm
    rng = np.random.RandomState(12)
    prompts = [rng.randint(0, 50, size=n).tolist()
               for n in (3, 7, 5, 2)]
    temps = (0.0, 0.0, 0.9, 0.0)

    def run(recorder):
        eng = DecodeEngine(spec, params, page_size=4, max_batch=2,
                           seed=7, recorder=recorder)
        rids = [eng.submit(p, 5, temperature=t)
                for p, t in zip(prompts, temps)]
        eng.run_until_idle()
        return [eng.result(r, timeout=10.0)["tokens"] for r in rids]

    rec = spans_lib.SpanRecorder(str(tmp_path))
    traced = run(rec)
    rec.close()
    assert run(None) == traced


def test_engine_loop_failure_emits_error_spans(lm, tmp_path,
                                               monkeypatch):
    """An engine-loop death marks every in-flight request's lifecycle
    with an error span (no retire follows), so reconstruction — and
    the SLO error-rate metric — sees the failure instead of a
    silently truncated stream."""
    from distributed_tensorflow_example_tpu.obs import slo as slo_lib
    from distributed_tensorflow_example_tpu.obs import (
        spans as spans_lib,
    )

    spec, params = lm
    rec = spans_lib.SpanRecorder(str(tmp_path))
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2,
                       recorder=rec)
    monkeypatch.setattr(
        eng, "step",
        lambda: (_ for _ in ()).throw(RuntimeError("boom tick")))
    # queue BEFORE starting the doomed loop (the same race the
    # pending-fast test documents): the in-flight-death scenario
    # needs the request accepted first
    rid = eng.submit([1, 2, 3], 4)
    eng.start()
    assert "boom tick" in eng.result(rid, timeout=10.0)["error"]
    eng.stop()
    rec.close()
    rows = spans_lib.read_spans(rec.path)
    recs = spans_lib.reconstruct(rows)
    assert "boom tick" in recs[(0, rid)]["error"]
    assert not recs[(0, rid)]["complete"]
    records = slo_lib.records_from_spans(rows)
    assert len(records) == 1 and records[0]["error"] is True


def test_live_trace_serves_from_recorder_ring(lm, tmp_path):
    """With a traced engine attached, /trace and /slo read the
    recorder's in-memory ring — the StatusServer pointed at an EMPTY
    logs dir (no span files) still serves the live lifecycles."""
    import urllib.request

    from distributed_tensorflow_example_tpu.obs import (
        spans as spans_lib,
    )
    from distributed_tensorflow_example_tpu.obs.serve import (
        StatusServer,
    )

    spec, params = lm
    rec = spans_lib.SpanRecorder(str(tmp_path / "spans_dir"))
    eng = DecodeEngine(spec, params, page_size=8, max_batch=2,
                       recorder=rec)
    rid = eng.submit([1, 2, 3], 3)
    eng.run_until_idle()
    assert eng.result(rid, timeout=10.0) is not None
    empty = tmp_path / "empty_logs"
    empty.mkdir()
    srv = StatusServer(str(empty), engine=eng)
    port = srv.start(0)
    assert port
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace?rid={rid}",
                timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["record"]["complete"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo", timeout=10) as r:
            slo = json.loads(r.read())
        assert slo["requests"] == 1
    finally:
        srv.close()
        rec.close()


# --- int8 KV pages (ISSUE 11 leg a) --------------------------------------


def test_init_paged_cache_int8_layout():
    """quant='int8': pools flip to int8 and gain f32 per-row/per-head
    scale planes [num_pages, page_size, H]; unknown formats are
    rejected at init."""
    spec = _spec()
    c = kvc.init_paged_cache(spec, 6, 4, quant="int8")
    for i in range(spec.num_blocks):
        assert np.asarray(c[f"k{i}"]).dtype == np.int8
        assert np.asarray(c[f"v{i}"]).dtype == np.int8
        assert np.asarray(c[f"k{i}_s"]).dtype == np.float32
        assert c[f"k{i}_s"].shape == (6, 4, spec.n_heads)
        assert c[f"v{i}_s"].shape == (6, 4, spec.n_heads)
    # the unquantized pool carries no scale planes
    assert "k0_s" not in kvc.init_paged_cache(spec, 6, 4)
    with pytest.raises(ValueError, match="int8"):
        kvc.init_paged_cache(spec, 6, 4, quant="int4")


@pytest.mark.parametrize("page_size", [4, 8, 16])
def test_int8_engine_matches_unquantized_greedy(lm, page_size):
    """THE kv-quant acceptance: greedy decode through the full
    DecodeEngine with --kv_quant=int8 is TOKEN-IDENTICAL to the
    unquantized pool, across page sizes, ragged prompt lengths, and
    admission churn (6 requests through 3 slots) — int8 rounding
    perturbs the logits within a bound that never flips the argmax
    on this suite."""
    spec, params = lm
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 50, size=n).tolist()
               for n in (3, 7, 5, 11, 2, 8)]
    n_new = 6
    outs = {}
    for quant in ("", "int8"):
        eng = DecodeEngine(spec, params, page_size=page_size,
                           max_batch=3, kv_quant=quant)
        rids = [eng.submit(p, n_new) for p in prompts]
        eng.run_until_idle()
        outs[quant] = [eng.result(r, timeout=10.0)["tokens"]
                       for r in rids]
    assert outs["int8"] == outs[""]


def test_int8_paged_decode_logit_error_bounded(lm):
    """Chained int8 paged decode vs the unquantized pool on identical
    token streams: logits within a small absolute bound AND the
    greedy argmax identical at every step — the 'bounded logit error'
    half of the acceptance, at two page sizes straddling the
    position count."""
    spec, params = lm
    b, steps = 3, 9
    rng = np.random.RandomState(8)
    toks = rng.randint(0, 50, size=(steps, b)).astype(np.int32)
    for page_size in (4, 16):
        per = steps // page_size + 1
        npages = 1 + b * per
        bt = jnp.asarray([[1 + i * per + j for j in range(per)]
                          for i in range(b)], jnp.int32)
        ref = kvc.init_paged_cache(spec, npages, page_size)
        q = kvc.init_paged_cache(spec, npages, page_size, quant="int8")
        for pos in range(steps):
            posv = jnp.full((b,), pos, jnp.int32)
            lr, ref = kvc.paged_decode_step(
                spec, params, ref, bt, jnp.asarray(toks[pos]), posv)
            lq, q = kvc.paged_decode_step(
                spec, params, q, bt, jnp.asarray(toks[pos]), posv)
            err = float(np.max(np.abs(np.asarray(lr) - np.asarray(lq))))
            assert err < 0.1, (page_size, pos, err)
            np.testing.assert_array_equal(
                np.argmax(np.asarray(lr), -1),
                np.argmax(np.asarray(lq), -1))


def test_int8_prefill_matches_stepwise_int8_decode(lm):
    """Prefill into int8 pages vs token-by-token int8 decode of the
    same prompt: block 0's quantized rows AND scale planes are
    BITWISE identical (block-0 k/v depend only on the embedded
    tokens, so equality pins the shared per-row/per-head quantization
    convention of the two write paths), and the first generated
    token's argmax agrees (deeper blocks read dequantized history
    stepwise vs exact history batched, so their logits drift within a
    small bound rather than matching bitwise)."""
    spec, params = lm
    rng = np.random.RandomState(9)
    page_size = 4
    lens = (3, 6)
    prompts = [rng.randint(0, 50, size=n).astype(np.int32)
               for n in lens]
    pb = 8
    toks = np.zeros((2, pb), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    paged = kvc.init_paged_cache(spec, 7, page_size, quant="int8")
    bt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    logits, paged = kvc.prefill_into_pages(
        spec, params, paged, bt, jnp.asarray(toks),
        jnp.asarray(lens, jnp.int32))
    for i, p in enumerate(prompts):
        # stepwise int8 reference for this prompt alone
        ref = kvc.init_paged_cache(spec, 3, page_size, quant="int8")
        rbt = jnp.asarray([[1, 2]], jnp.int32)
        for pos, t in enumerate(p):
            rl, ref = kvc.paged_decode_step(
                spec, params, ref, rbt, jnp.asarray([t], jnp.int32),
                jnp.asarray([pos], jnp.int32))
        assert int(np.argmax(np.asarray(logits)[i])) == int(
            np.argmax(np.asarray(rl)[0]))
        np.testing.assert_allclose(np.asarray(logits)[i],
                                   np.asarray(rl)[0], atol=0.1)
        # block-0 convention pin: prompt i's rows in the shared pool
        # == the stepwise pool's rows, values AND scales, bitwise
        for name in ("k0", "v0", "k0_s", "v0_s"):
            for pos in range(len(p)):
                page, rowi = divmod(pos, page_size)
                mine = np.asarray(paged[name])[
                    int(bt[i, page]), rowi]
                theirs = np.asarray(ref[name])[
                    int(rbt[0, page]), rowi]
                np.testing.assert_array_equal(mine, theirs,
                                              err_msg=(name, i, pos))


@needs_stack
def test_tp_sharded_paged_cache_parity(lm, devices8):
    """Paged decode with the KV pool's heads split Megatron-style over
    a ('model',) mesh: each shard writes/gathers its local heads'
    pages, the row-split projections psum, and the logits — hence the
    greedy chain — match the unsharded paged decode exactly (the
    generate_sharded precedent, on the paged layout)."""
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_example_tpu.parallel import (
        mesh as mesh_lib,
    )

    spec = _spec(n_heads=4)
    params = tfm.init(jax.random.PRNGKey(8), spec)
    mesh = mesh_lib.build_mesh(1, 2)
    pspecs = tfm.param_pspecs(spec, model_axis="model")
    placed = jax.device_put(
        params, mesh_lib.shardings_for(mesh, pspecs))
    page_size, steps, b = 4, 6, 2
    bt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    cache_specs = {k: P(None, None, "model")
                   for k in kvc.init_paged_cache(spec, 5, page_size)}

    def run(p, cache, tok, pos):
        logits, cache = kvc.paged_decode_step(
            spec, p, cache, bt, tok, pos, model_axis="model")
        return logits, cache

    fn = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(pspecs, cache_specs, P(), P()),
        out_specs=(P(), cache_specs)))
    ref_cache = kvc.init_paged_cache(spec, 5, page_size)
    tp_cache = jax.device_put(
        kvc.init_paged_cache(spec, 5, page_size),
        mesh_lib.shardings_for(mesh, cache_specs))
    tok = jnp.asarray([7, 11], jnp.int32)
    for pos in range(steps):
        posv = jnp.full((b,), pos, jnp.int32)
        ref_logits, ref_cache = kvc.paged_decode_step(
            spec, params, ref_cache, bt, tok, posv)
        tp_logits, tp_cache = fn(placed, tp_cache, tok, posv)
        tok = jnp.argmax(ref_logits, -1).astype(jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(jnp.argmax(tp_logits, -1)), np.asarray(tok))
