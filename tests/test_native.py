"""Native C++ helper tests: native and numpy fallback paths agree."""

import numpy as np

from distributed_tensorflow_example_tpu import native


def test_gather_batch_matches_fallback():
    rng = np.random.RandomState(0)
    images = rng.rand(50, 12).astype(np.float32)
    labels = rng.rand(50, 4).astype(np.float32)
    idx = rng.permutation(50)[:16].astype(np.int64)
    gi, gl = native.gather_batch(images, labels, idx)
    np.testing.assert_array_equal(gi, images[idx])
    np.testing.assert_array_equal(gl, labels[idx])


def test_u8_to_f32_scaled():
    arr = np.arange(256, dtype=np.uint8).reshape(16, 16)
    out = native.u8_to_f32_scaled(arr)
    np.testing.assert_allclose(out, arr.astype(np.float32) / 255.0, rtol=1e-6)
    assert out.dtype == np.float32


def test_native_availability_is_boolean():
    assert native.native_available() in (True, False)
