"""Transformer model family tests (models/transformer.py): init/apply
contracts against a pure-numpy oracle, dense-vs-flash backend parity
(incl. the kernel path at a tile-aligned length), sharded-step
equivalences on the 8-device mesh (DP, Megatron TP, both SP layouts,
dense/sparse/top-2 MoE incl. the aux loss, and every 2x2x2 3-axis TP
crossing), the lm objective (training, KV-cached decode/generate),
dropout, and the full driver end-to-end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models import transformer as tfm

from conftest import needs_stack  # noqa: E402


def _spec(**kw):
    base = dict(input_size=784, num_classes=10, seq_len=28, d_model=32,
                n_heads=2, num_blocks=2, d_ff=64)
    base.update(kw)
    return tfm.TransformerSpec(**base)


def test_init_shapes_and_determinism():
    spec = _spec()
    p1 = tfm.init(jax.random.PRNGKey(1), spec)
    p2 = tfm.init(jax.random.PRNGKey(1), spec)
    assert p1["W_in"].shape == (28, 32)
    assert p1["pos"].shape == (28, 32)
    assert p1["L1_Wqkv"].shape == (32, 3, 32)
    assert p1["W_head"].shape == (32, 10)
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k])
    assert tfm.num_params(spec) == sum(int(v.size) for v in p1.values())


def test_forward_shape_and_determinism():
    spec = _spec()
    params = tfm.init(jax.random.PRNGKey(1), spec)
    x = np.random.RandomState(0).rand(4, 784).astype(np.float32)
    out = jax.jit(lambda p, xx: tfm.apply(spec, p, xx))(params, x)
    assert out.shape == (4, 10)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_flash_backend_matches_dense(causal):
    """seq_len=256 (tile-aligned): the flash backend runs the Pallas
    kernel (interpret mode on CPU) and must match the dense backend."""
    kw = dict(input_size=1024, seq_len=256, d_model=64, n_heads=2,
              num_blocks=1, d_ff=32, causal=causal)
    sd = _spec(attention="dense", **kw)
    sf = _spec(attention="flash", **kw)
    params = tfm.init(jax.random.PRNGKey(2), sd)
    x = np.random.RandomState(1).rand(2, 1024).astype(np.float32)
    want = np.asarray(jax.jit(lambda p, xx: tfm.apply(sd, p, xx))(params, x))
    got = np.asarray(jax.jit(lambda p, xx: tfm.apply(sf, p, xx))(params, x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_dp8_matches_single_device(devices8):
    """One sync step on the 8-device data-parallel mesh == the same
    step on one device (the psum-equivalence guarantee, extended to the
    transformer family)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec()
    cfg = Config(model="transformer", learning_rate=0.01)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(3)
    x = rng.rand(16, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]

    results = {}
    for dp in (1, 8):
        mesh = mesh_lib.build_mesh(dp, 1, devices=devices8[:dp])
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, acc = step(state, x, y)
        results[dp] = (jax.tree.map(np.asarray, new_state.params),
                       float(cost))
    for k in results[1][0]:
        np.testing.assert_allclose(
            results[8][0][k], results[1][0][k], rtol=2e-5, atol=2e-6,
            err_msg=k)
    assert abs(results[8][1] - results[1][1]) < 1e-5


def test_end_to_end_training_learns(tmp_path):
    """Full driver with --model=transformer: fast scan path, summaries
    with the transformer graph event, eval — learns the synthetic set."""
    import glob

    from distributed_tensorflow_example_tpu.train.loop import run
    from distributed_tensorflow_example_tpu.utils.summary import read_event_file

    res = run(Config(
        model="transformer", training_epochs=2, batch_size=64,
        learning_rate=0.003, optimizer="adam",
        synthetic_train_size=2048, synthetic_test_size=512,
        logs_path=str(tmp_path), frequency=16, compilation_cache="",
    ))
    assert res["test_accuracy"] >= 0.8, res
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    events = read_event_file(files[0])
    graphs = [e for e in events if e["graph_nodes"]]
    names = {n["name"] for n in graphs[0]["graph_nodes"]}
    assert "block0/attention" in names and "block1/ffn" in names


def test_cli_flags():
    from distributed_tensorflow_example_tpu.config import parse_config
    from distributed_tensorflow_example_tpu.train.loop import make_spec

    cfg = parse_config([
        "--model=transformer", "--d_model=64", "--n_heads=8",
        "--num_blocks=3", "--seq_len=16", "--attention=flash", "--causal",
    ])
    spec = make_spec(cfg)
    assert spec.d_model == 64 and spec.n_heads == 8
    assert spec.num_blocks == 3 and spec.seq_len == 16
    assert spec.attention == "flash" and spec.causal
    # --pallas implies the flash backend too
    spec2 = make_spec(parse_config(["--model=transformer", "--pallas"]))
    assert spec2.attention == "flash"
    # the MLP-default sigmoid doesn't leak into this family
    assert spec2.activation == "gelu"


def test_tp_validation():
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib

    # degrees that don't divide the heads / hidden dim are rejected
    with pytest.raises(ValueError, match="n_heads=2"):
        mesh_lib.layer_styles(_spec(), 4)
    with pytest.raises(ValueError, match="d_ff=36"):
        mesh_lib.layer_styles(_spec(n_heads=8, d_ff=36), 8)
    # MoE+TP is allowed (attention TP-shards; the expert FFNs shard
    # over the expert axis) and the d_ff check applies to the dense
    # FFN only
    mesh_lib.layer_styles(_spec(num_experts=4, d_ff=35), 2)


@pytest.mark.parametrize("mp", [2, 4], ids=["tp2", "dp4xtp2"])
def test_tp_step_matches_single_device(devices8, mp):
    """One sync step on a ('data','model') mesh — Megatron head/FFN
    sharding inside the step, two psums per block — must match the
    same step on one device (tensor parallelism is a layout, not a
    math change). Covers both the pure-TP and the DPxTP crossing."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(n_heads=4)
    cfg = Config(model="transformer", learning_rate=0.01, model_parallel=mp)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(7)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(mesh, mp_):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, mp_))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]), 1)
    ptp, ctp = one(mesh_lib.build_mesh(8 // mp, mp, devices=devices8), mp)
    assert abs(c1 - ctp) < 1e-5
    for k in p1:
        np.testing.assert_allclose(ptp[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_tp_driver_end_to_end(devices8, tmp_path):
    """Full driver run with --model=transformer --model_parallel=2 on
    the DP4xTP2 mesh: fast scan path, sharded optimizer state, eval."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", model_parallel=2, training_epochs=1,
        batch_size=32, learning_rate=0.003, optimizer="adam",
        n_heads=4, synthetic_train_size=512, synthetic_test_size=128,
        logs_path=str(tmp_path), summaries=False, frequency=8,
        compilation_cache="",
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 0.15   # one epoch: above chance


@pytest.mark.parametrize("flavor", ["sp", "pp", "ep", "ulysses",
                                    "ep_sparse"])
def test_3d_tp_crossings_match_single_device(devices8, flavor):
    """2x2x2 three-axis meshes — ('data', seq|stage|expert, 'model') —
    crossing Megatron TP with each other parallelism flavor must match
    the single-device step (all compositions are layouts, not math)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.models import transformer as tfm_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    kw = dict(n_heads=4)
    ckw = dict(model="transformer", learning_rate=0.01, n_heads=4,
               model_parallel=2)
    if flavor in ("sp", "ulysses"):
        builder, pkw = mesh_lib.build_seq_mesh, {}
        if flavor == "ulysses":
            kw["sp_impl"] = ckw["sp_impl"] = "ulysses"
        ckw["sequence_parallel"] = 2
    elif flavor == "pp":
        builder, pkw = mesh_lib.build_stage_mesh, {}
        ckw.update(pipeline_parallel=2, microbatches=2)
    else:
        builder, pkw = mesh_lib.build_expert_mesh, {}
        kw["num_experts"] = 4
        ckw.update(num_experts=4, expert_parallel=2)
        if flavor == "ep_sparse":
            # sparse dispatch: tokens shard over 'expert' too (ample
            # capacity so no drops -> exact layout equivalence)
            kw.update(moe_dispatch="alltoall", capacity_factor=4.0)
            ckw.update(moe_dispatch="alltoall", capacity_factor=4.0)
    spec = _spec(**kw)
    cfg = Config(**ckw)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(11)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def run_step(mesh, mp, pipeline):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        if pipeline:
            state = tfm_lib.pipeline_train_state(spec, opt, state)
            sspecs = mesh_lib.pipeline_state_pspecs(
                spec, opt, mesh_lib.STAGE_AXIS,
                mesh_lib.tp_axis(spec, mp))
        else:
            sspecs = mesh_lib.state_pspecs(
                spec, opt, mp,
                mesh_lib.axis_if_present(mesh, mesh_lib.EXPERT_AXIS))
        state = mesh_lib.place_state(state, mesh, sspecs)
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        params = new_state.params
        if pipeline:
            params = tfm_lib.pipeline_unstack_params(
                spec, jax.tree.map(np.asarray, params))
        return jax.tree.map(np.asarray, params), float(cost)

    cfg1 = cfg.replace(model_parallel=1, sequence_parallel=1,
                       expert_parallel=1, pipeline_parallel=1)
    opt1 = make_optimizer(cfg1)
    state1 = create_train_state(jax.random.PRNGKey(1), spec, opt1)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    state1 = mesh_lib.place_state(
        state1, mesh1, mesh_lib.state_pspecs(spec, opt1, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt1)
    s1, c1, _ = step1(state1, x, y)
    p1 = jax.tree.map(np.asarray, s1.params)

    mesh3 = builder(2, 2, devices=devices8, model_parallel=2, **pkw)
    p3, c3 = run_step(mesh3, 2, flavor == "pp")
    assert abs(c1 - c3) < 1e-5
    for k in p1:
        np.testing.assert_allclose(p3[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


@pytest.mark.parametrize("grouped", [False, True],
                         ids=["einsum", "grouped_kernel"])
def test_moe_alltoall_matches_dense_with_ample_capacity(grouped):
    """capacity_factor >= E means no token ever drops, so the sparse
    (capacity-limited, Switch/GShard-style) dispatch computes exactly
    the dense dispatch's math: top-1 expert output scaled by the gate
    probability. ``grouped`` runs the same equivalence with the fused
    Pallas expert kernel (--grouped_moe) in place of the einsums."""
    kw = dict(num_experts=4, n_heads=2)
    sd = _spec(moe_dispatch="dense", **kw)
    ss = _spec(moe_dispatch="alltoall", capacity_factor=4.0,
               grouped_moe=grouped, **kw)
    params = tfm.init(jax.random.PRNGKey(3), sd)
    x = np.random.RandomState(2).rand(4, 784).astype(np.float32)
    want = np.asarray(jax.jit(lambda p, xx: tfm.apply(sd, p, xx))(params, x))
    got = np.asarray(jax.jit(lambda p, xx: tfm.apply(ss, p, xx))(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_ln_apply_matches_reference():
    """--fused_ln swaps every LayerNorm (block ln1/ln2, final lnf) for
    the Pallas kernels, with ln2 fusing the attention residual add:
    the classify forward AND its parameter gradients must match the
    reference path (same f32 math, kernel-tile reduction order
    aside)."""
    spec_ref = _spec()
    spec_fus = _spec(fused_ln=True)
    params = tfm.init(jax.random.PRNGKey(3), spec_ref)
    rng = np.random.RandomState(2)
    x = rng.rand(4, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4)]
    want = np.asarray(jax.jit(
        lambda p, xx: tfm.apply(spec_ref, p, xx))(params, x))
    got = np.asarray(jax.jit(
        lambda p, xx: tfm.apply(spec_fus, p, xx))(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def loss(sp):
        def f(p):
            logits = tfm.apply(sp, p, x)
            return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), -1))

        return f

    g_ref = jax.grad(loss(spec_ref))(params)
    g_fus = jax.grad(loss(spec_fus))(params)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_fus[k]),
                                   np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_fused_ln_generate_matches_reference():
    """Greedy generation (the rank-2 decode LN sites) is token-
    identical with and without --fused_ln."""
    spec_ref = _lm_spec(num_blocks=1)
    spec_fus = _lm_spec(num_blocks=1, fused_ln=True)
    params = tfm.init(jax.random.PRNGKey(6), spec_ref)
    prompt = jnp.asarray(np.random.RandomState(1).randint(
        0, 16, (2, 8)).astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(tfm.generate(spec_ref, params, prompt)),
        np.asarray(tfm.generate(spec_fus, params, prompt)))


def test_moe_alltoall_drops_overflow_tokens():
    """A tiny capacity forces overflow: the run still executes (dropped
    tokens ride the residual stream) and the result diverges from the
    no-drop dense dispatch."""
    kw = dict(num_experts=4, n_heads=2)
    ss = _spec(moe_dispatch="alltoall", capacity_factor=0.05, **kw)
    sd = _spec(moe_dispatch="dense", **kw)
    params = tfm.init(jax.random.PRNGKey(3), ss)
    x = np.random.RandomState(2).rand(4, 784).astype(np.float32)
    got = np.asarray(jax.jit(lambda p, xx: tfm.apply(ss, p, xx))(params, x))
    want = np.asarray(jax.jit(lambda p, xx: tfm.apply(sd, p, xx))(params, x))
    assert np.isfinite(got).all()
    assert np.abs(got - want).max() > 1e-4


@pytest.mark.parametrize("grouped", [False, True],
                         ids=["einsum", "grouped_kernel"])
def test_moe_top2_sparse_matches_dense_with_ample_capacity(grouped):
    """GShard top-2 routing: the sparse per-choice dispatch (2 slots
    per token) must equal the dense gate-weighted combination when
    nothing drops, and top-2 must actually mix two experts (differ
    from top-1) — with either expert-matmul realization."""
    kw = dict(num_experts=4, n_heads=2, moe_topk=2)
    sd = _spec(moe_dispatch="dense", **kw)
    ss = _spec(moe_dispatch="alltoall", capacity_factor=4.0,
               grouped_moe=grouped, **kw)
    s1 = _spec(moe_dispatch="dense", num_experts=4, n_heads=2)
    params = tfm.init(jax.random.PRNGKey(3), sd)
    x = np.random.RandomState(2).rand(4, 784).astype(np.float32)
    want = np.asarray(jax.jit(lambda p, xx: tfm.apply(sd, p, xx))(params, x))
    got = np.asarray(jax.jit(lambda p, xx: tfm.apply(ss, p, xx))(params, x))
    top1 = np.asarray(jax.jit(lambda p, xx: tfm.apply(s1, p, xx))(params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert np.abs(want - top1).max() > 1e-4  # two experts really mix


def test_moe_top2_ep_step_matches_single_device(devices8):
    """One top-2 sparse-EP step on the DP2xEP2 mesh == the
    single-device top-2 sparse step (ample capacity)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_experts=4, moe_dispatch="alltoall", moe_topk=2,
                 capacity_factor=4.0)
    cfg = Config(model="transformer", learning_rate=0.01, num_experts=4,
                 moe_dispatch="alltoall", moe_topk=2, capacity_factor=4.0)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(17)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(mesh, expert_axis):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1, expert_axis))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]), None)
    pep, cep = one(mesh_lib.build_expert_mesh(2, 2, devices=devices8[:4]),
                   mesh_lib.EXPERT_AXIS)
    assert abs(c1 - cep) < 1e-5
    for kk in p1:
        np.testing.assert_allclose(pep[kk], p1[kk], rtol=3e-5, atol=3e-6,
                                   err_msg=kk)


def test_moe_top2_first_choices_win_under_overflow():
    """GShard priority: under tight capacity, every token's FIRST
    choice claims buffer space before any token's second choice.
    Construction: 2 experts, 4 tokens; tokens 0-1 route top1->e1
    top2->e0, tokens 2-3 top1->e0 top2->e1; capacity 2 per expert.
    With rank-major priority each expert's buffer holds exactly the
    two FIRST choices, so every second choice drops and the output is
    each token's first-expert FFN scaled by its renormalized top gate.
    (Token-major interleaving would instead let tokens 0-1's runner-up
    choices evict tokens 2-3's first choices from e0.)"""
    import jax.numpy as jnp

    d, ff, e = 8, 16, 2
    # cap = ceil(0.5 * T=4 * k=2 / E=2) = 2 slots per expert
    spec = tfm.TransformerSpec(
        input_size=32, seq_len=4, d_model=d, n_heads=2, num_blocks=1,
        d_ff=ff, num_experts=e, moe_topk=2, moe_dispatch="alltoall",
        capacity_factor=0.5)
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(1, 4, d).astype(np.float32))
    # router: logit margin decides top-1; e0 column keyed to feature 0
    wr = np.zeros((d, e), np.float32)
    wr[0, 0], wr[0, 1] = 1.0, -1.0
    a = a.at[0, 0, 0].set(-3.0).at[0, 1, 0].set(-3.0)   # t0,t1 -> e1
    a = a.at[0, 2, 0].set(3.0).at[0, 3, 0].set(3.0)     # t2,t3 -> e0
    params = {
        "L0_Wr": jnp.asarray(wr),
        "L0_We1": jnp.asarray(rng.randn(e, d, ff).astype(np.float32)),
        "L0_be1": jnp.zeros((e, ff), jnp.float32),
        "L0_We2": jnp.asarray(rng.randn(e, ff, d).astype(np.float32)),
        "L0_be2": jnp.zeros((e, d), jnp.float32),
    }
    act = jax.nn.gelu
    bp = {k[len("L0_"):]: v for k, v in params.items()}
    out, _aux = tfm._moe_ffn_sparse(spec, bp, a, act, jnp.float32, None)
    got = np.asarray(out)

    # oracle: first choices only, renormalized top gate
    probs = np.asarray(jax.nn.softmax(np.asarray(a) @ wr, axis=-1))[0]
    def expert_ffn(x_tok, ei):
        h1 = np.asarray(act(x_tok @ np.asarray(params["L0_We1"][ei])))
        return h1 @ np.asarray(params["L0_We2"][ei])
    want = np.zeros((4, d), np.float32)
    for tkn in range(4):
        top1 = int(np.argmax(probs[tkn]))
        g = np.sort(probs[tkn])[::-1]
        gate0 = g[0] / (g[0] + g[1])
        want[tkn] = gate0 * expert_ffn(np.asarray(a)[0, tkn], top1)
    np.testing.assert_allclose(got[0], want, rtol=2e-5, atol=2e-5)


def test_moe_aux_loss_oracle_and_dispatch_agreement():
    """The load-balance aux loss matches a numpy re-derivation
    (E * sum_e f_e * P_e per block, averaged over blocks) and both
    dispatches report the same value (they share the router)."""
    kw = dict(num_experts=4, n_heads=2, aux_loss_weight=0.01)
    sd = _spec(moe_dispatch="dense", **kw)
    ss = _spec(moe_dispatch="alltoall", capacity_factor=4.0, **kw)
    params = tfm.init(jax.random.PRNGKey(3), sd)
    x = np.random.RandomState(2).rand(4, 784).astype(np.float32)
    _, aux_d = jax.jit(
        lambda p, xx: tfm.apply(sd, p, xx, with_aux=True))(params, x)
    _, aux_s = jax.jit(
        lambda p, xx: tfm.apply(ss, p, xx, with_aux=True))(params, x)
    np.testing.assert_allclose(float(aux_d), float(aux_s), rtol=1e-5)
    # direct oracle on one block's probs
    probs = np.asarray(jax.nn.softmax(
        np.random.RandomState(5).randn(32, 4).astype(np.float32), -1))
    f = np.bincount(probs.argmax(-1), minlength=4) / probs.shape[0]
    want = 4 * float(np.sum(f * probs.mean(0)))
    got = float(tfm._load_balance_loss(
        sd, jnp.asarray(probs), jnp.asarray(probs.argmax(-1))))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # aux >= 1 at the balanced optimum; random routers sit above it
    assert float(aux_d) >= 0.99


def test_moe_aux_loss_changes_grads_not_reported_cost(devices8):
    """With --moe_aux_weight the optimized objective gains the
    balance term (different params after one step) while the REPORTED
    cost stays the plain CE of the same forward."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    rng = np.random.RandomState(23)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]
    mesh = mesh_lib.build_mesh(1, 1, devices=devices8[:1])

    def one(w):
        spec = _spec(num_experts=4, aux_loss_weight=w)
        cfg = Config(model="transformer", learning_rate=0.05,
                     num_experts=4, moe_aux_weight=w)
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p0, c0 = one(0.0)
    p1, c1 = one(0.5)
    assert abs(c0 - c1) < 1e-6          # reported cost: plain CE
    router_moved = np.abs(p1["L0_Wr"] - p0["L0_Wr"]).max()
    assert router_moved > 1e-7          # the balance term reached grads


@pytest.mark.parametrize("mode", ["dp8", "sp", "ep_sparse"])
def test_moe_aux_loss_sharded_matches_single_device(devices8, mode):
    """With the aux loss ON, sharded training must still equal the
    single-device step: the balance statistics (f, P) are pmean'd over
    every token-sharding axis before combining, so each shard adds the
    GLOBAL-batch aux (a per-shard aux would make mean-of-products
    diverge from the single-device objective)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    kw = dict(num_experts=4, aux_loss_weight=0.3, n_heads=4)
    ckw = dict(model="transformer", learning_rate=0.05, num_experts=4,
               moe_aux_weight=0.3, n_heads=4)
    if mode == "ep_sparse":
        kw.update(moe_dispatch="alltoall", capacity_factor=4.0)
        ckw.update(moe_dispatch="alltoall", capacity_factor=4.0)
    spec = _spec(**kw)
    cfg = Config(**ckw)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(29)
    x = rng.rand(16, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]

    def one(mesh, expert_axis):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1, expert_axis))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]), None)
    if mode == "dp8":
        mesh = mesh_lib.build_mesh(8, 1, devices=devices8)
        ea = None
    elif mode == "sp":
        mesh = mesh_lib.build_seq_mesh(2, 4, devices=devices8)
        ea = None
    else:
        mesh = mesh_lib.build_expert_mesh(2, 4, devices=devices8)
        ea = mesh_lib.EXPERT_AXIS
    pn, cn = one(mesh, ea)
    assert abs(c1 - cn) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pn[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_moe_topk_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="moe_topk"):
        run(Config(model="transformer", num_experts=4, moe_topk=5))
    with pytest.raises(ValueError, match="moe_topk"):
        run(Config(model="transformer", num_experts=4, moe_topk=0))


def test_moe_alltoall_ep_step_matches_single_device(devices8):
    """Sparse-dispatch expert parallelism shards TOKENS over the
    expert axis too (the GShard layout): a DP2xEP4 step with ample
    capacity must match the single-device sparse step — the two
    all_to_alls and the doubled batch axes are layout, not math."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_experts=4, moe_dispatch="alltoall",
                 capacity_factor=4.0)
    cfg = Config(model="transformer", learning_rate=0.01, num_experts=4,
                 moe_dispatch="alltoall", capacity_factor=4.0)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(13)
    x = rng.rand(16, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]

    def one(mesh, expert_axis):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1, expert_axis))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]), None)
    mesh_ep = mesh_lib.build_expert_mesh(2, 4, devices=devices8)
    assert step_lib.sparse_ep_mode(mesh_ep, spec)
    pep, cep = one(mesh_ep, mesh_lib.EXPERT_AXIS)
    assert abs(c1 - cep) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pep[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_moe_alltoall_driver_end_to_end(devices8, tmp_path):
    """Full driver run: --num_experts 4 --expert_parallel 2
    --moe_dispatch alltoall on the DP4xEP2 mesh (host loop; tokens
    sharded over both axes)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", num_experts=4, expert_parallel=2,
        moe_dispatch="alltoall", training_epochs=1, batch_size=32,
        learning_rate=0.003, optimizer="adam",
        synthetic_train_size=512, synthetic_test_size=128,
        logs_path=str(tmp_path), summaries=False, frequency=8,
        compilation_cache="",
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])


def _lm_spec(**kw):
    base = dict(input_size=64, num_classes=10, seq_len=64, d_model=32,
                n_heads=4, num_blocks=2, d_ff=64, objective="lm",
                vocab_size=16, causal=True)
    base.update(kw)
    return tfm.TransformerSpec(**base)


def test_lm_forward_shapes_and_tokenize():
    spec = _lm_spec()
    params = tfm.init(jax.random.PRNGKey(1), spec)
    assert params["W_emb"].shape == (16, 32)
    assert params["W_head"].shape == (32, 16)
    assert "W_in" not in params
    x = np.random.RandomState(0).rand(4, 64).astype(np.float32)
    out = jax.jit(lambda p, xx: tfm.apply(spec, p, xx))(params, x)
    assert out.shape == (4, 64, 16)        # per-position vocab logits
    toks = np.asarray(tfm.tokenize(spec, x))
    assert toks.shape == (4, 64) and toks.min() >= 0 and toks.max() <= 15
    np.testing.assert_array_equal(toks, np.clip(np.round(x * 15), 0, 15))


def test_lm_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="model=transformer"):
        run(Config(objective="lm"))
    # (lm x pipeline_parallel is SUPPORTED since r4 — covered by
    # test_pp_lm_and_interleaved_match_single_device and the driver
    # end-to-end test)
    with pytest.raises(ValueError, match="seq_len"):
        _lm_spec(seq_len=32).d_feature


@pytest.mark.parametrize("mode", ["dp8", "sp_ring", "sp_ulysses"])
def test_lm_step_matches_single_device(devices8, mode):
    """Next-token training is exact under sharding: DP splits examples;
    SP splits the token axis, where each shard's boundary target (the
    next shard's first token) arrives via ppermute and the position
    sums are psum'd — both must reproduce the single-device step."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    sp_impl = "ulysses" if mode == "sp_ulysses" else "ring"
    spec = _lm_spec(sp_impl=sp_impl)
    cfg = Config(model="transformer", objective="lm", input_size=64,
                 vocab_size=16, learning_rate=0.01, n_heads=4,
                 sp_impl=sp_impl)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(31)
    x = rng.rand(8, 64).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]  # unused

    def one(mesh):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, acc = step(state, x, y)
        return (jax.tree.map(np.asarray, new_state.params), float(cost),
                float(acc))

    p1, c1, a1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]))
    mesh = (mesh_lib.build_mesh(8, 1, devices=devices8) if mode == "dp8"
            else mesh_lib.build_seq_mesh(2, 4, devices=devices8))
    pn, cn, an = one(mesh)
    assert abs(c1 - cn) < 1e-5 and abs(a1 - an) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pn[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_lm_driver_learns(devices8, tmp_path):
    """Full driver --objective=lm: next-token accuracy well above the
    1/vocab chance after two epochs on the synthetic set."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", objective="lm", input_size=64,
        d_model=32, n_heads=4, num_blocks=2, d_ff=64, vocab_size=16,
        training_epochs=2, batch_size=32, learning_rate=0.003,
        optimizer="adam", synthetic_train_size=512,
        synthetic_test_size=128, logs_path=str(tmp_path),
        summaries=False, frequency=8, compilation_cache="",
    ))
    assert res["test_accuracy"] > 0.3, res   # chance = 1/16
    assert np.isfinite(res["final_cost"])


def test_dropout_train_vs_eval(devices8):
    """Dropout drops in training only: a rate-0 step equals the
    baseline exactly, a rate>0 step is deterministic per (seed, step)
    but differs from rate-0, and the EVAL forward ignores the rate
    entirely (no rng reaches it)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    rng = np.random.RandomState(41)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]
    mesh = mesh_lib.build_mesh(1, 1, devices=devices8[:1])

    def one(rate):
        spec = _spec(dropout_rate=rate)
        cfg = Config(model="transformer", learning_rate=0.01,
                     dropout_rate=rate)
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p_base, c_base = one(0.0)
    p_a, c_a = one(0.5)
    p_b, c_b = one(0.5)
    assert abs(c_a - c_b) < 1e-12          # deterministic per step
    for k in p_a:
        np.testing.assert_array_equal(p_a[k], p_b[k])
    assert abs(c_a - c_base) > 1e-6        # masks actually dropped

    # eval ignores the rate: identical logits either way
    spec0, spec5 = _spec(), _spec(dropout_rate=0.5)
    params = tfm.init(jax.random.PRNGKey(2), spec0)
    out0 = np.asarray(jax.jit(
        lambda p, xx: tfm.apply(spec0, p, xx))(params, x))
    out5 = np.asarray(jax.jit(
        lambda p, xx: tfm.apply(spec5, p, xx))(params, x))
    np.testing.assert_array_equal(out0, out5)


def test_dropout_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="transformer only"):
        run(Config(dropout_rate=0.1))
    # r5: fsdp + dropout is supported; async local-SGD stays gated
    with pytest.raises(ValueError, match="synchronous"):
        run(Config(model="transformer", dropout_rate=0.1,
                   sync_period=3))


def test_dropout_fsdp_matches_sync_step(devices8):
    """Dropout under FSDP (r5, VERDICT r4 next #2): the FSDP step
    derives its per-shard dropout rng from the same (seed, step,
    data-index) stream as the sync step, so an FSDP-with-dropout step
    over dp=8 must reproduce the plain sync dropout step's update."""
    from distributed_tensorflow_example_tpu.parallel import fsdp as fsdp_lib
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(dropout_rate=0.3)
    cfg = Config(model="transformer", learning_rate=0.01,
                 dropout_rate=0.3, data_parallel=8)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(43)
    x = rng.rand(16, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]
    mesh = mesh_lib.build_mesh(8, 1, devices=devices8)

    st_s = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st_s = mesh_lib.place_state(st_s, mesh,
                                mesh_lib.state_pspecs(spec, opt, 1))
    sync = step_lib.build_train_step(cfg, mesh, spec, opt)
    new_s, c_s, _ = sync(st_s, x, y)
    p_s = jax.tree.map(np.asarray, new_s.params)

    cfg_f = cfg.replace(fsdp=True)
    full = jax.tree.map(
        np.asarray, create_train_state(jax.random.PRNGKey(1), spec, opt))
    st_f = fsdp_lib.shard_state_host(full, 8)
    st_f = mesh_lib.place_state(st_f, mesh, fsdp_lib.fsdp_specs(st_f))
    fstep = fsdp_lib.build_fsdp_train_step(cfg_f, mesh, spec, opt, full)
    new_f, c_f, _ = fstep(st_f, x, y)
    gather = fsdp_lib.build_gather_params(mesh, full)
    p_f = jax.tree.map(np.asarray, gather(new_f))

    assert abs(float(c_s) - float(c_f)) < 1e-6
    for k in p_s:
        np.testing.assert_allclose(p_f[k], p_s[k], rtol=2e-6, atol=2e-7,
                                   err_msg=k)


def test_dropout_pp_deterministic_and_distinct(devices8):
    """Dropout under PP (r5): the pipelined step is deterministic per
    (seed, step), drops (differs from rate-0), decorrelates masks
    across microbatches (differs from a 1-microbatch run), and trains
    through the driver."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    rng = np.random.RandomState(47)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(rate, microbatches):
        spec = _spec(dropout_rate=rate, num_blocks=2)
        cfg = Config(model="transformer", learning_rate=0.01,
                     dropout_rate=rate, pipeline_parallel=2,
                     num_blocks=2, microbatches=microbatches)
        opt = make_optimizer(cfg)
        mesh = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
        st = create_train_state(jax.random.PRNGKey(1), spec, opt)
        st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
        st = mesh_lib.place_state(
            st, mesh,
            mesh_lib.pipeline_state_pspecs(spec, opt,
                                           mesh_lib.STAGE_AXIS))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        _, cost, _ = step(st, x, y)
        return float(cost)

    c_a = one(0.5, 2)
    c_b = one(0.5, 2)
    assert abs(c_a - c_b) < 1e-12          # deterministic per step
    c_0 = one(0.0, 2)
    assert abs(c_a - c_0) > 1e-6           # masks actually dropped
    c_m1 = one(0.5, 1)
    assert abs(c_a - c_m1) > 1e-9          # per-microbatch streams


def test_dropout_driver_trains(devices8, tmp_path):
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", dropout_rate=0.1, training_epochs=1,
        batch_size=32, learning_rate=0.003, optimizer="adam",
        synthetic_train_size=512, synthetic_test_size=128,
        logs_path=str(tmp_path), summaries=False, frequency=8,
        compilation_cache="",
    ))
    assert np.isfinite(res["final_cost"]), res


@pytest.mark.parametrize("variant", ["f32", "bf16", "moe", "fused_ln"])
def test_lm_decode_matches_teacher_forcing(variant):
    """KV-cached decode_step computes the training forward: feeding a
    full token sequence position by position must reproduce apply()'s
    per-position logits (the cache IS the attention state) — in f32,
    in bfloat16 (the cache stores the same rounded k/v the training
    attention consumes), with a MoE FFN (ample-capacity sparse
    training == the dense routing decode computes), and with the
    fused Pallas LayerNorms (the decode path's rank-2 kernel calls
    against the training forward's rank-3 ones)."""
    import jax.numpy as jnp2

    kw = dict(num_blocks=2)
    tol = 2e-4
    if variant == "bf16":
        kw["compute_dtype"] = jnp2.bfloat16
        tol = 3e-2   # bf16 rounding; argmax-relevant scale
    elif variant == "moe":
        kw.update(num_experts=4, moe_dispatch="alltoall",
                  capacity_factor=4.0)   # ample: sparse == dense
    elif variant == "fused_ln":
        kw["fused_ln"] = True
    spec = _lm_spec(**kw)
    params = tfm.init(jax.random.PRNGKey(5), spec)
    rng = np.random.RandomState(9)
    x = rng.rand(2, 64).astype(np.float32)
    tokens = tfm.tokenize(spec, jnp.asarray(x))           # [2, 64]
    want = np.asarray(jax.jit(
        lambda p, xx: tfm.apply(spec, p, xx))(params, x))  # [2, 64, V]

    cache = tfm.init_decode_cache(spec, 2)
    step = jax.jit(lambda c, t, p: tfm.decode_step(spec, params, c, t, p))
    got = []
    for pos in range(spec.seq_len):
        logits, cache = step(cache, tokens[:, pos], pos)
        got.append(np.asarray(logits))
    got = np.stack(got, axis=1)
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)


def test_lm_generate_contract():
    """generate(): prompt preserved, completions in-vocab, greedy is
    deterministic, sampled differs across keys but not across calls
    with the same key."""
    spec = _lm_spec(num_blocks=1)
    params = tfm.init(jax.random.PRNGKey(6), spec)
    prompt = jnp.asarray(np.random.RandomState(1).randint(
        0, 16, (2, 8)).astype(np.int32))
    g = np.asarray(tfm.generate(spec, params, prompt))
    assert g.shape == (2, 64)
    np.testing.assert_array_equal(g[:, :8], np.asarray(prompt))
    assert g.min() >= 0 and g.max() < 16
    np.testing.assert_array_equal(
        g, np.asarray(tfm.generate(spec, params, prompt)))
    s1 = np.asarray(tfm.generate(spec, params, prompt,
                                 rng=jax.random.PRNGKey(1)))
    s2 = np.asarray(tfm.generate(spec, params, prompt,
                                 rng=jax.random.PRNGKey(1)))
    s3 = np.asarray(tfm.generate(spec, params, prompt,
                                 rng=jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(s1, s2)
    assert (s1 != s3).any()


def test_tp_sharded_decode_matches_single_device(devices8):
    """generate_sharded on a ('model',)-mesh (VERDICT r3 next #8):
    heads split over 'model' with shard-local KV caches, Wo/W2 psums —
    greedy AND sampled tokens must equal the single-device decode
    exactly (the psum'd logits are identical on every shard, and every
    shard draws with the same key)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib

    spec = _lm_spec(num_blocks=2, n_heads=4)
    params = tfm.init(jax.random.PRNGKey(8), spec)
    prompt = jnp.asarray(np.random.RandomState(2).randint(
        0, 16, (2, 8)).astype(np.int32))
    mesh = mesh_lib.build_mesh(1, 2)
    placed = jax.device_put(
        params, mesh_lib.shardings_for(
            mesh, tfm.param_pspecs(spec, model_axis="model")))
    for rng in (None, jax.random.PRNGKey(3)):
        want = np.asarray(tfm.generate(spec, params, prompt, rng=rng,
                                       temperature=0.7))
        got = np.asarray(tfm.generate_sharded(
            spec, placed, prompt, mesh, "model", rng=rng,
            temperature=0.7))
        np.testing.assert_array_equal(got, want)


def test_tp_decode_driver_samples_on_mesh(devices8, tmp_path):
    """--sample_after with live Megatron TP: sampling runs on the mesh
    (no host param fetch) and writes valid tokens."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", objective="lm", input_size=32,
        vocab_size=16, d_model=32, n_heads=2, num_blocks=2, d_ff=64,
        causal=True, model_parallel=2, data_parallel=4,
        training_epochs=1, batch_size=32, learning_rate=0.003,
        optimizer="adam", dataset="synthetic",
        synthetic_train_size=256, synthetic_test_size=64,
        summaries=False, compilation_cache="", frequency=4,
        sample_after=2, logs_path=str(tmp_path / "logs"),
    ))
    assert np.isfinite(res["final_cost"])
    import os

    with np.load(os.path.join(str(tmp_path / "logs"),
                              "samples.npz")) as z:
        samples = z["samples"]
    assert samples.shape == (2, 32)
    assert samples.min() >= 0 and samples.max() < 16


def test_tp_param_pspecs_shard_blocks_only():
    from jax.sharding import PartitionSpec as P

    spec = _spec(n_heads=4)
    pp = tfm.param_pspecs(spec, model_axis="model")
    assert pp["L0_Wqkv"] == P(None, None, "model")
    assert pp["L0_Wo"] == P("model", None)
    assert pp["L0_W1"] == P(None, "model")
    assert pp["L0_b1"] == P("model")
    assert pp["L0_W2"] == P("model", None)
    assert pp["L0_b2"] == P()
    for name in ("W_in", "pos", "W_head", "lnf_g", "L0_ln1_g"):
        assert pp[name] == P(), name


def test_bad_seq_len_rejected():
    with pytest.raises(ValueError, match="not divisible"):
        _spec(seq_len=30).d_feature


def test_ulysses_head_divisibility_rejected():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="ulysses shards attention heads"):
        run(Config(model="transformer", sequence_parallel=4,
                   sp_impl="ulysses", n_heads=2))


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_sp_step_matches_single_device(devices8, causal, sp_impl):
    """One sync step on the ('data','seq') 2x4 mesh — the selected
    sequence-parallel layout (ppermute ring or ulysses head<->seq
    all_to_all) inside the step, token axis sharded — must match the
    same step on one device (sequence parallelism is a layout, not a
    math change)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(causal=causal, n_heads=4, sp_impl=sp_impl)
    cfg = Config(model="transformer", learning_rate=0.01, causal=causal,
                 n_heads=4, sp_impl=sp_impl)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(5)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(mesh):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]))
    psp, csp = one(mesh_lib.build_seq_mesh(2, 4, devices=devices8))
    assert abs(c1 - csp) < 1e-5
    for k in p1:
        np.testing.assert_allclose(psp[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_sp_driver_end_to_end(devices8):
    """--sequence_parallel through the full driver (host loop), SP4xDP2:
    trains and evals with the token axis sharded across the mesh."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", sequence_parallel=4, data_parallel=2,
        training_epochs=1, batch_size=64, learning_rate=0.003,
        optimizer="adam", synthetic_train_size=1024,
        synthetic_test_size=256, summaries=False, compilation_cache="",
        frequency=8,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 0.2  # 1 short epoch; chance is 0.10


def test_sp_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="model=transformer"):
        run(Config(sequence_parallel=2))
    with pytest.raises(ValueError, match="divide evenly"):
        run(Config(model="transformer", sequence_parallel=5, seq_len=28))
    with pytest.raises(ValueError, match="no fsdp"):
        run(Config(model="transformer", sequence_parallel=2, fsdp=True))


def test_fsdp_matches_plain_dp(devices8):
    """--fsdp with the transformer family: ZeRO-3 sharding is a layout
    change, so a short training run must land where plain DP lands."""
    from distributed_tensorflow_example_tpu.train.loop import run

    def go(**kw):
        return run(Config(
            model="transformer", data_parallel=8, training_epochs=1,
            batch_size=64, learning_rate=0.003, optimizer="adam",
            synthetic_train_size=512, synthetic_test_size=128,
            summaries=False, compilation_cache="", frequency=8, **kw,
        ))

    plain = go()
    fsdp = go(fsdp=True)
    assert abs(plain["final_cost"] - fsdp["final_cost"]) < 1e-4, (
        plain["final_cost"], fsdp["final_cost"])
    # reduction-order drift can flip a borderline argmax on the tiny
    # barely-trained eval set; allow one example's worth of slack
    assert abs(plain["test_accuracy"] - fsdp["test_accuracy"]) <= 1 / 128


def test_moe_single_expert_equals_dense_ffn():
    """E=1 MoE with the dense FFN's weights is exactly the dense FFN
    (router has one choice; gate prob = 1)."""
    sd = _spec()
    sm = _spec(num_experts=1)
    pd_ = tfm.init(jax.random.PRNGKey(3), sd)
    pm = {k: v for k, v in pd_.items() if "_W1" not in k and "_b1" not in k
          and "_W2" not in k and "_b2" not in k}
    for i in range(sd.num_blocks):
        pm[f"L{i}_Wr"] = jnp.zeros((sd.d_model, 1))
        pm[f"L{i}_We1"] = pd_[f"L{i}_W1"][None]
        pm[f"L{i}_be1"] = pd_[f"L{i}_b1"][None]
        pm[f"L{i}_We2"] = pd_[f"L{i}_W2"][None]
        pm[f"L{i}_be2"] = pd_[f"L{i}_b2"][None]
    x = np.random.RandomState(7).rand(4, 784).astype(np.float32)
    want = np.asarray(jax.jit(lambda p, xx: tfm.apply(sd, p, xx))(pd_, x))
    got = np.asarray(jax.jit(lambda p, xx: tfm.apply(sm, p, xx))(pm, x))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_ep_step_matches_single_device(devices8):
    """One sync step on the ('data','expert') 2x4 mesh — expert weights
    and FLOPs sharded 1/4 per device, partial outputs psum-combined —
    must match the same MoE step on one device."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_experts=4)
    cfg = Config(model="transformer", num_experts=4, learning_rate=0.01)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(9)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(mesh, expert_axis):
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh,
            mesh_lib.state_pspecs(spec, opt, 1, expert_axis))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(mesh_lib.build_mesh(1, 1, devices=devices8[:1]), None)
    pep, cep = one(mesh_lib.build_expert_mesh(2, 4, devices=devices8),
                   mesh_lib.EXPERT_AXIS)
    assert abs(c1 - cep) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pep[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_moe_driver_end_to_end(devices8):
    """--num_experts --expert_parallel through the full driver: trains
    with expert weights sharded across the mesh."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", num_experts=4, expert_parallel=4,
        data_parallel=2, training_epochs=1, batch_size=64,
        learning_rate=0.003, optimizer="adam", synthetic_train_size=1024,
        synthetic_test_size=256, summaries=False, compilation_cache="",
        frequency=8,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 0.2


def test_ep_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="num_experts > 0"):
        run(Config(model="transformer", expert_parallel=2))
    with pytest.raises(ValueError, match="divide evenly"):
        run(Config(model="transformer", num_experts=3, expert_parallel=2))
    with pytest.raises(ValueError, match="transformer only"):
        run(Config(num_experts=4))


def test_pipeline_stack_roundtrip():
    spec = _spec()
    p = tfm.init(jax.random.PRNGKey(4), spec)
    stacked = tfm.pipeline_stack_params(spec, p)
    assert stacked["blk_Wqkv"].shape == (2, 32, 3, 32)
    back = tfm.pipeline_unstack_params(spec, stacked)
    assert set(back) == set(p)
    for k in p:
        np.testing.assert_array_equal(back[k], p[k])


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_pp_step_matches_single_device(devices8, microbatches):
    """One sync step on the ('data','stage') 2x2 mesh — blocks split
    across stages, activations hopping via ppermute on the GPipe
    schedule — must match the same step on one device (microbatching
    is a schedule, not a math change)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import (
        TrainState, create_train_state)

    spec = _spec()
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, microbatches=microbatches)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(13)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    # single-device baseline (plain layout)
    cfg1 = Config(model="transformer", learning_rate=0.01)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, _ = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    # pipelined (stacked layout, 2 stages x 2 data shards)
    meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    stacked = tfm.pipeline_stack_params(spec, st.params)
    st = TrainState(step=st.step, params=stacked,
                    opt_state=opt.init(stacked))
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(spec, opt, mesh_lib.STAGE_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, _ = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params))

    assert abs(c1 - float(cp)) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_pp_driver_end_to_end(devices8):
    """--pipeline_parallel through the full driver, PP2xDP4: trains and
    evals with the blocks staged across the mesh."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", pipeline_parallel=2, num_blocks=2,
        data_parallel=4, microbatches=4, training_epochs=1,
        batch_size=64, learning_rate=0.003, optimizer="adam",
        synthetic_train_size=1024, synthetic_test_size=256,
        summaries=False, compilation_cache="", frequency=8,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 0.2


def test_pp_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="model=transformer"):
        run(Config(pipeline_parallel=2))
    with pytest.raises(ValueError, match="divide evenly"):
        run(Config(model="transformer", pipeline_parallel=3, num_blocks=2))
    # r5: PP x MoE incl. the balance loss AND every TP crossing are
    # supported; only seq x expert under PP stays rejected
    with pytest.raises(ValueError, match="not both"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, num_experts=4, expert_parallel=2,
                   sequence_parallel=2))
    with pytest.raises(ValueError, match="pipeline_parallel > 1"):
        run(Config(model="transformer", virtual_stages=2))
    with pytest.raises(ValueError, match="virtual_stages"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, virtual_stages=2))
    with pytest.raises(ValueError, match="divisible by pipeline_parallel"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=4, virtual_stages=2, microbatches=3))


def test_pipeline_stack_roundtrip_interleaved():
    """virtual=2 stacking permutes blocks so each stage's contiguous
    shard holds its interleaved chunks: nb=4, p=2, v=2 -> stacked order
    [0, 2, 1, 3] (stage 0 executes blocks 0 then 2)."""
    spec = _spec(num_blocks=4)
    p = tfm.init(jax.random.PRNGKey(6), spec)
    stacked = tfm.pipeline_stack_params(spec, p, n_stages=2, virtual=2)
    for pos, j in enumerate([0, 2, 1, 3]):
        np.testing.assert_array_equal(stacked["blk_W1"][pos],
                                      p[f"L{j}_W1"])
    back = tfm.pipeline_unstack_params(spec, stacked, n_stages=2,
                                       virtual=2)
    assert set(back) == set(p)
    for k in p:
        np.testing.assert_array_equal(back[k], p[k])


@pytest.mark.parametrize("objective,virtual,microbatches", [
    ("lm", 1, 4),          # VERDICT r3 next #4: PP x the lm objective
    ("classify", 2, 2),    # interleaved virtual stages (bubble / v)
    ("lm", 2, 4),          # both at once
], ids=["lm-gpipe", "classify-interleaved", "lm-interleaved"])
def test_pp_lm_and_interleaved_match_single_device(devices8, objective,
                                                   virtual, microbatches):
    """The lm objective pipelines with its loss statistics computed on
    the last stage (two numbers per example ride the psum, never the
    [mb, S, V] logits), and Megatron interleaved virtual stages
    re-chunk the same math — both must match the single-device step
    exactly."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import (
        TrainState, create_train_state)

    kw = dict(num_blocks=4)
    if objective == "lm":
        kw.update(objective="lm", input_size=32, seq_len=32,
                  vocab_size=16, causal=True)
    spec = _spec(**kw)
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, num_blocks=4,
                 microbatches=microbatches, virtual_stages=virtual)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(17)
    x = rng.rand(8, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    # single-device baseline (plain layout)
    cfg1 = Config(model="transformer", learning_rate=0.01)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, a1 = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    # pipelined (stacked layout, 2 stages x 2 data shards)
    meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, virtual)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(spec, opt, mesh_lib.STAGE_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params), 2, virtual)

    assert abs(c1 - float(cp)) < 1e-5
    assert abs(a1 - float(ap)) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


@pytest.mark.parametrize("objective", ["classify", "lm"])
def test_pp_sp_matches_single_device(devices8, objective):
    """PP x SP (r4): a ('data','stage','seq') 2x2x2 mesh — microbatch
    token axes sharded over 'seq' with ring attention inside every
    pipeline chunk, stage hops carrying [mb, S/n_seq, D] blocks — must
    match the single-device step (for lm, the shard-boundary target
    ppermute and seq psums run inside the last stage's head)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    kw = dict(num_blocks=2)
    if objective == "lm":
        kw.update(objective="lm", input_size=32, seq_len=32,
                  vocab_size=16, causal=True)
    spec = _spec(**kw)
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, sequence_parallel=2,
                 num_blocks=2, microbatches=2)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(23)
    x = rng.rand(8, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    cfg1 = Config(model="transformer", learning_rate=0.01)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, a1 = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8,
                                      sequence_parallel=2)
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(spec, opt, mesh_lib.STAGE_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params), 2, 1)

    assert abs(c1 - float(cp)) < 2e-5
    assert abs(a1 - float(ap)) < 2e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


@pytest.mark.parametrize("dispatch", ["dense", "alltoall"])
def test_pp_ep_matches_single_device(devices8, dispatch):
    """PP x EP (r4): MoE blocks pipeline with their router/expert
    leaves stacked and the expert stacks sharded over the inner
    'expert' axis — the per-chunk expert psum (dense dispatch) or
    all_to_all exchange (sparse, ample capacity so nothing drops)
    must reproduce the single-device step."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    kw = dict(num_blocks=2, num_experts=4, moe_dispatch=dispatch)
    if dispatch == "alltoall":
        kw["capacity_factor"] = 4.0   # no drops -> exact equivalence
    spec = _spec(**kw)
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, expert_parallel=2, num_blocks=2,
                 num_experts=4, moe_dispatch=dispatch, microbatches=2,
                 **({"capacity_factor": 4.0}
                    if dispatch == "alltoall" else {}))
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(29)
    x = rng.rand(8, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    cfg1 = Config(model="transformer", learning_rate=0.01,
                  num_experts=4, moe_dispatch=dispatch,
                  **({"capacity_factor": 4.0}
                     if dispatch == "alltoall" else {}))
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, a1 = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8,
                                      expert_parallel=2)
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(
            spec, opt, mesh_lib.STAGE_AXIS, None, mesh_lib.EXPERT_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params), 2, 1)

    assert abs(c1 - float(cp)) < 2e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


@pytest.mark.parametrize("dispatch", ["dense", "alltoall"])
def test_pp_moe_aux_matches_single_device(devices8, dispatch):
    """The MoE balance loss under PP (r5, VERDICT r4 next #2): per-tick
    (f, P) router statistics accumulated across microbatches and
    combined after the schedule must optimize the exact single-device
    objective — the updated params (whose gradients flow through the
    aux term) match the flat step's."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    kw = dict(num_blocks=2, num_experts=4, moe_dispatch=dispatch,
              aux_loss_weight=0.05)
    if dispatch == "alltoall":
        kw["capacity_factor"] = 4.0   # no drops -> exact equivalence
    spec = _spec(**kw)
    moe_cfg = dict(num_experts=4, moe_dispatch=dispatch,
                   moe_aux_weight=0.05,
                   **({"capacity_factor": 4.0}
                      if dispatch == "alltoall" else {}))
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, expert_parallel=2, num_blocks=2,
                 microbatches=2, **moe_cfg)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(41)
    x = rng.rand(8, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    cfg1 = Config(model="transformer", learning_rate=0.01, **moe_cfg)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, _ = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8,
                                      expert_parallel=2)
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(
            spec, opt, mesh_lib.STAGE_AXIS, None, mesh_lib.EXPERT_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, _ = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params), 2, 1)

    assert abs(c1 - float(cp)) < 2e-5   # reported cost stays plain CE
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_pp_ep_driver_end_to_end(devices8):
    """--pipeline_parallel x --expert_parallel through the full driver
    (sparse dispatch: tokens shard over 'expert' too)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", num_experts=4, moe_dispatch="alltoall",
        d_model=16, n_heads=2, num_blocks=2, d_ff=32,
        pipeline_parallel=2, expert_parallel=2, data_parallel=2,
        microbatches=2, training_epochs=1, batch_size=32,
        learning_rate=0.003, optimizer="adam", dataset="synthetic",
        synthetic_train_size=256, synthetic_test_size=64,
        summaries=False, compilation_cache="", frequency=4,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 0.1


def test_pp_sp_driver_end_to_end(devices8):
    """--pipeline_parallel x --sequence_parallel through the full
    driver (the 'composes with data and tensor parallelism only' gate
    is gone)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", objective="lm", input_size=32,
        vocab_size=16, d_model=32, n_heads=2, num_blocks=2, d_ff=64,
        causal=True, pipeline_parallel=2, sequence_parallel=2,
        data_parallel=2, microbatches=2, training_epochs=1,
        batch_size=32, learning_rate=0.003, optimizer="adam",
        dataset="synthetic", synthetic_train_size=256,
        synthetic_test_size=64, summaries=False, compilation_cache="",
        frequency=4,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 1.0 / 16


def test_apply_pipeline_rejects_virtual_on_one_stage():
    """Library-level guard (ADVICE r4): virtual > 1 with n_stages == 1
    must raise in apply_pipeline itself — the wrap ppermute is gated on
    p > 1, so chunks beyond the first would silently consume stale
    zeros for callers that bypass the driver's validation."""
    spec = tfm.TransformerSpec(input_size=32, seq_len=8, d_model=16,
                               n_heads=2, num_blocks=2, d_ff=32)
    params = tfm.init(jax.random.PRNGKey(0), spec)
    stacked = tfm.pipeline_stack_params(spec, params, 1, 1)
    x = np.zeros((4, 32), np.float32)
    with pytest.raises(ValueError, match="virtual=2 needs n_stages"):
        tfm.apply_pipeline(spec, stacked, x, "stage", n_stages=1,
                           num_microbatches=2, virtual=2)


@pytest.mark.parametrize("objective", ["classify", "lm"])
def test_pp_sp_tp_matches_single_device(devices8, objective):
    """The standard 4D recipe (r5, VERDICT r4 next #2): PP x SP x TP
    on a ('data','stage','seq','model') 1x2x2x2 mesh — ring attention
    across seq shards of TP-local heads inside every pipeline chunk,
    Megatron psums over 'model', stage hops over 'stage' — must match
    the single-device step."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    kw = dict(num_blocks=2)
    if objective == "lm":
        kw.update(objective="lm", input_size=32, seq_len=32,
                  vocab_size=16, causal=True)
    spec = _spec(**kw)
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, sequence_parallel=2,
                 model_parallel=2, num_blocks=2, microbatches=2)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(31)
    x = rng.rand(4, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4)]

    cfg1 = Config(model="transformer", learning_rate=0.01)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, a1 = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    meshp = mesh_lib.build_stage_mesh(1, 2, devices=devices8,
                                      sequence_parallel=2,
                                      model_parallel=2)
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(
            spec, opt, mesh_lib.STAGE_AXIS, mesh_lib.MODEL_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params), 2, 1)

    assert abs(c1 - float(cp)) < 2e-5
    assert abs(a1 - float(ap)) < 2e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_pp_ep_tp_matches_single_device(devices8):
    """PP x EP x TP (r5): ('data','stage','expert','model') 1x2x2x2 —
    expert stacks shard over 'expert' while the attention side of
    every pipelined block Megatron-shards over 'model'."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_blocks=2, num_experts=4)
    cfg = Config(model="transformer", learning_rate=0.01,
                 pipeline_parallel=2, expert_parallel=2,
                 model_parallel=2, num_blocks=2, num_experts=4,
                 microbatches=2)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(37)
    x = rng.rand(4, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4)]

    cfg1 = Config(model="transformer", learning_rate=0.01, num_experts=4)
    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, a1 = step1(st1, x, y)
    p1 = jax.tree.map(np.asarray, new1.params)

    meshp = mesh_lib.build_stage_mesh(1, 2, devices=devices8,
                                      expert_parallel=2,
                                      model_parallel=2)
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(
            spec, opt, mesh_lib.STAGE_AXIS, mesh_lib.MODEL_AXIS,
            mesh_lib.EXPERT_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params), 2, 1)

    assert abs(c1 - float(cp)) < 2e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_pp_sp_tp_driver_end_to_end(devices8):
    """The 4D crossing through the full driver: --pipeline_parallel x
    --sequence_parallel x --model_parallel (x data) in one run."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", objective="lm", input_size=32,
        vocab_size=16, d_model=32, n_heads=2, num_blocks=2, d_ff=64,
        causal=True, pipeline_parallel=2, sequence_parallel=2,
        model_parallel=2, data_parallel=1, microbatches=2,
        training_epochs=1, batch_size=32, learning_rate=0.003,
        optimizer="adam", dataset="synthetic",
        synthetic_train_size=128, synthetic_test_size=32,
        summaries=False, compilation_cache="", frequency=4,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 1.0 / 16


def test_pp_sp_ep_rejected():
    """seq- and expert-sharding together under PP stays rejected
    (token-sharded sparse capacity pools are not defined)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="not both"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, num_experts=4, sequence_parallel=2,
                   expert_parallel=2))


def test_pp_interleaved_resume_layout_guard(devices8, tmp_path):
    """virtual_stages>1 permutes the stacked block order, so resuming
    under a different pipeline layout must be rejected (the shapes
    would match and restore silently permuted blocks)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        model="transformer", pipeline_parallel=2, num_blocks=4,
        data_parallel=4, microbatches=2, batch_size=32,
        learning_rate=0.003, optimizer="adam", dataset="synthetic",
        synthetic_train_size=128, synthetic_test_size=64,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=str(tmp_path),
    )
    run(Config(training_epochs=1, virtual_stages=2, **kw))
    with pytest.raises(ValueError, match="pinned to that layout"):
        run(Config(training_epochs=2, resume=True, virtual_stages=1,
                   **kw))


def test_pp_lm_driver_end_to_end(devices8, tmp_path):
    """--objective=lm x --pipeline_parallel x --virtual_stages through
    the full driver: trains, evals next-token accuracy, and samples
    (the sampling path un-stacks the pipeline layout at the run's
    (stages, virtual))."""
    import os

    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", objective="lm", input_size=32,
        vocab_size=16, d_model=32, n_heads=2, num_blocks=4, d_ff=64,
        causal=True, pipeline_parallel=2, virtual_stages=2,
        data_parallel=4, microbatches=2, training_epochs=1,
        batch_size=32, learning_rate=0.003, optimizer="adam",
        synthetic_train_size=256, synthetic_test_size=64,
        summaries=False, compilation_cache="", frequency=4,
        sample_after=2, logs_path=str(tmp_path / "logs"),
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    # next-token accuracy above the 1/16 chance floor
    assert res["test_accuracy"] > 1.0 / 16
    # the samples exist and are valid tokens of the run's vocab
    with np.load(os.path.join(str(tmp_path / "logs"),
                              "samples.npz")) as z:
        samples = z["samples"]
    assert samples.shape == (2, 32)
    assert samples.min() >= 0 and samples.max() < 16


def test_pp_checkpoint_resume(devices8, tmp_path):
    """PP checkpoints store the stacked layout; --resume continues a
    pipeline run at the same stage count with the step counter intact."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        model="transformer", pipeline_parallel=2, num_blocks=2,
        data_parallel=4, microbatches=2, batch_size=64,
        learning_rate=0.003, optimizer="adam", dataset="synthetic",
        synthetic_train_size=512, synthetic_test_size=128,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=str(tmp_path),
    )
    first = run(Config(training_epochs=1, **kw))
    assert first["steps"] == 8
    resumed = run(Config(training_epochs=2, resume=True, **kw))
    assert resumed["steps"] == 16, resumed
    assert np.isfinite(resumed["final_cost"])


def test_forward_matches_numpy_oracle():
    """apply() against an independent pure-numpy re-derivation of the
    pre-LN encoder (embed+pos, LN, qkv in the [d,3,d] layout, softmax
    attention, gelu FFN, mean-pool head) — the same style of oracle
    that pins the MLP family to the reference math."""
    spec = _spec(num_blocks=2, n_heads=2)
    params = jax.tree.map(np.asarray,
                          tfm.init(jax.random.PRNGKey(11), spec))
    x = np.random.RandomState(3).rand(3, 784).astype(np.float32)

    def ln(h, g, b):
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        return (h - mu) / np.sqrt(var + 1e-6) * g + b

    def gelu(v):
        # explicit tanh-approximation formula — independent of
        # jax.nn.gelu (which the model itself uses)
        return 0.5 * v * (1.0 + np.tanh(
            np.sqrt(2.0 / np.pi) * (v + 0.044715 * v ** 3)))

    def softmax(v):
        e = np.exp(v - v.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    b, s, f, d = 3, 28, 28, 32
    h = x.reshape(b, s, f) @ params["W_in"] + params["b_in"] \
        + params["pos"][None]
    for i in range(2):
        a = ln(h, params[f"L{i}_ln1_g"], params[f"L{i}_ln1_b"])
        qkv = np.einsum("bsd,dte->bste", a, params[f"L{i}_Wqkv"]) \
            + params[f"L{i}_bqkv"]
        q, k, v = (qkv[:, :, t].reshape(b, s, 2, 16) for t in range(3))
        scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(16.0)
        att = np.einsum("bhqk,bkhd->bqhd", softmax(scores), v)
        h = h + att.reshape(b, s, d) @ params[f"L{i}_Wo"] \
            + params[f"L{i}_bo"]
        a = ln(h, params[f"L{i}_ln2_g"], params[f"L{i}_ln2_b"])
        a = gelu(a @ params[f"L{i}_W1"] + params[f"L{i}_b1"])
        h = h + a @ params[f"L{i}_W2"] + params[f"L{i}_b2"]
    h = ln(h, params["lnf_g"], params["lnf_b"])
    want = h.mean(1) @ params["W_head"] + params["b_head"]

    got = np.asarray(jax.jit(
        lambda p, xx: tfm.apply(spec, p, xx))(params, x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_tp_checkpoint_resume(devices8, tmp_path, capsys):
    """Checkpoint + --resume with the transformer TP-sharded state:
    saving gathers the model-axis shards into the portable unsharded
    layout (asserted on the written leaf shapes) and resume actually
    continues from it ("Resumed from" print; step counter resumes)."""
    from distributed_tensorflow_example_tpu.train.loop import run
    from distributed_tensorflow_example_tpu.utils import checkpoint as C

    ckpt = str(tmp_path / "ck")
    common = dict(
        model="transformer", model_parallel=2, n_heads=4,
        training_epochs=1, batch_size=32, learning_rate=0.003,
        optimizer="adam", synthetic_train_size=256,
        synthetic_test_size=64, logs_path=str(tmp_path),
        summaries=False, frequency=8, compilation_cache="",
        checkpoint_dir=ckpt,
    )
    r1 = run(Config(**common))
    assert r1["steps"] == 8
    path = C.latest_checkpoint(ckpt)
    with np.load(path) as z:
        assert int(z["__step__"]) == 8
        # portable unsharded layout: the FULL [d, 3, d] qkv leaf, not
        # a model-axis shard
        assert z[".params/L0_Wqkv"].shape == (128, 3, 128)
    capsys.readouterr()
    r2 = run(Config(**{**common, "training_epochs": 2, "resume": True}))
    assert "Resumed from" in capsys.readouterr().out
    assert r2["steps"] == 16       # continued, not restarted
    assert np.isfinite(r2["final_cost"])


def test_lm_sample_after_driver(devices8, tmp_path, capsys):
    """--sample_after: the driver generates prompt-conditioned samples
    after LM training and saves them next to the logs."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", objective="lm", input_size=64,
        d_model=32, n_heads=4, num_blocks=1, d_ff=64, vocab_size=16,
        training_epochs=1, batch_size=32, learning_rate=0.003,
        optimizer="adam", synthetic_train_size=256,
        synthetic_test_size=64, logs_path=str(tmp_path),
        summaries=False, frequency=8, compilation_cache="",
        sample_after=3, sample_temperature=0.8,
    ))
    assert np.isfinite(res["final_cost"])
    assert "Sampled 3 sequences" in capsys.readouterr().out
    with np.load(str(tmp_path / "samples.npz")) as z:
        s = z["samples"]
        assert s.shape == (3, 64)
        assert s.min() >= 0 and s.max() < 16
        assert int(z["prompt_len"]) == 8


def test_sample_after_requires_lm():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="objective=lm"):
        run(Config(model="transformer", sample_after=2))


def test_sample_temperature_validation():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="sample_temperature"):
        run(Config(model="transformer", objective="lm", input_size=64,
                   sample_after=2, sample_temperature=-1.0))


def test_lm_grad_accum_matches_full_batch(devices8):
    """--grad_accum under the lm objective: the accumulated step must
    equal the plain step on the same batch (mean of equal-chunk
    next-token losses == the full-batch loss; gradients likewise)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _lm_spec()
    rng = np.random.RandomState(53)
    x = rng.rand(8, 64).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]  # unused
    mesh = mesh_lib.build_mesh(1, 1, devices=devices8[:1])

    def one(accum):
        cfg = Config(model="transformer", objective="lm", input_size=64,
                     vocab_size=16, learning_rate=0.01, n_heads=4,
                     grad_accum=accum)
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        new_state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, new_state.params), float(cost)

    p1, c1 = one(1)
    p2, c2 = one(2)
    assert abs(c1 - c2) < 5e-6   # chunk-mean reassociation
    for k in p1:
        np.testing.assert_allclose(p2[k], p1[k], rtol=1e-5, atol=1e-7,
                                   err_msg=k)


# ---- 1F1B schedule (r5, VERDICT r4 next #4) ----


def _one_device_step(spec, opt, cfg1, x, y, devices8):
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    mesh1 = mesh_lib.build_mesh(1, 1, devices=devices8[:1])
    st1 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st1 = mesh_lib.place_state(st1, mesh1,
                               mesh_lib.state_pspecs(spec, opt, 1))
    step1 = step_lib.build_train_step(cfg1, mesh1, spec, opt)
    new1, c1, a1 = step1(st1, x, y)
    return jax.tree.map(np.asarray, new1.params), float(c1), float(a1)


@pytest.mark.parametrize("objective", ["classify", "lm"])
def test_pp_1f1b_matches_single_device(devices8, objective):
    """The fused-tick 1F1B schedule (pipeline_value_and_grad_1f1b) on
    a PP2 x DP2 mesh — forward and backward sub-slots interleaved so
    live microbatch stashes cap at 2p-1 — must produce the same step
    as one device: the schedule changes memory liveness, not math."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import (
        TrainState, create_train_state)

    kw = dict(num_blocks=2)
    extra = {}
    if objective == "lm":
        kw.update(objective="lm", input_size=32, seq_len=32,
                  vocab_size=16, causal=True)
        extra = dict(objective="lm", input_size=32, vocab_size=16)
    spec = _spec(**kw)
    cfg = Config(model="transformer", learning_rate=0.01, num_blocks=2,
                 pipeline_parallel=2, microbatches=4,
                 pp_schedule="1f1b", **extra)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(17)
    x = rng.rand(8, spec.input_size).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    cfg1 = Config(model="transformer", learning_rate=0.01, **extra)
    p1, c1, a1 = _one_device_step(spec, opt, cfg1, x, y, devices8)

    meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(spec, opt, mesh_lib.STAGE_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params))

    assert abs(c1 - float(cp)) < 2e-5
    assert abs(a1 - float(ap)) < 2e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_pp_1f1b_deep_tp_matches_single_device(devices8):
    """1F1B at p=4 (the schedule's warmup/steady/cooldown phases all
    exercised: ticks = M + 2(p-1) = 10) crossed with TP2 — Megatron
    psums transpose inside each backward sub-slot's vjp."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_blocks=4)
    cfg = Config(model="transformer", learning_rate=0.01, num_blocks=4,
                 pipeline_parallel=4, model_parallel=2, microbatches=4,
                 pp_schedule="1f1b")
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(19)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    cfg1 = Config(model="transformer", learning_rate=0.01, num_blocks=4)
    p1, c1, _a1 = _one_device_step(spec, opt, cfg1, x, y, devices8)

    meshp = mesh_lib.build_stage_mesh(1, 4, devices=devices8,
                                      model_parallel=2)
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 4, 1)
    st = mesh_lib.place_state(
        st, meshp,
        mesh_lib.pipeline_state_pspecs(
            spec, opt, mesh_lib.STAGE_AXIS, mesh_lib.MODEL_AXIS))
    stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
    newp, cp, _ap = stepp(st, x, y)
    pp_un = tfm.pipeline_unstack_params(
        spec, jax.tree.map(np.asarray, newp.params))

    assert abs(c1 - float(cp)) < 2e-5
    for k in p1:
        np.testing.assert_allclose(pp_un[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=k)


def test_pp_1f1b_dropout_matches_gpipe(devices8):
    """Dropout under 1F1B: the backward sub-slot re-derives each
    microbatch's fold_in rng bit-identically, and the schedule uses
    the same per-microbatch streams as gpipe — the two schedules must
    produce the SAME step from the same state."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_blocks=2, dropout_rate=0.2)
    rng = np.random.RandomState(23)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(schedule):
        cfg = Config(model="transformer", learning_rate=0.01,
                     num_blocks=2, dropout_rate=0.2,
                     pipeline_parallel=2, microbatches=4,
                     pp_schedule=schedule)
        opt = make_optimizer(cfg)
        meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
        st = create_train_state(jax.random.PRNGKey(1), spec, opt)
        st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
        st = mesh_lib.place_state(
            st, meshp,
            mesh_lib.pipeline_state_pspecs(spec, opt,
                                           mesh_lib.STAGE_AXIS))
        stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
        newp, cp, _ = stepp(st, x, y)
        return jax.tree.map(np.asarray, newp.params), float(cp)

    pg, cg = one("gpipe")
    pf, cf = one("1f1b")
    assert abs(cg - cf) < 1e-5
    for k in pg:
        np.testing.assert_allclose(pf[k], pg[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_pp_slot_remat_matches_plain(devices8):
    """--remat under the pipeline = per-slot jax.checkpoint: identical
    numbers, smaller liveness (backward stores only slot inputs)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = _spec(num_blocks=2)
    rng = np.random.RandomState(29)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(remat):
        cfg = Config(model="transformer", learning_rate=0.01,
                     num_blocks=2, pipeline_parallel=2, microbatches=2,
                     remat=remat)
        opt = make_optimizer(cfg)
        meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
        st = create_train_state(jax.random.PRNGKey(1), spec, opt)
        st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
        st = mesh_lib.place_state(
            st, meshp,
            mesh_lib.pipeline_state_pspecs(spec, opt,
                                           mesh_lib.STAGE_AXIS))
        stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
        newp, cp, _ = stepp(st, x, y)
        return jax.tree.map(np.asarray, newp.params), float(cp)

    p0, c0 = one(False)
    p1, c1 = one(True)
    assert abs(c0 - c1) < 1e-6
    for k in p0:
        np.testing.assert_allclose(p1[k], p0[k], rtol=1e-6, atol=1e-8,
                                   err_msg=k)


def test_pp_1f1b_driver_end_to_end(devices8):
    """--pp_schedule=1f1b through the full driver (train + eval)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    res = run(Config(
        model="transformer", pipeline_parallel=2, num_blocks=2,
        data_parallel=4, microbatches=4, pp_schedule="1f1b",
        training_epochs=1, batch_size=64, learning_rate=0.003,
        optimizer="adam", synthetic_train_size=1024,
        synthetic_test_size=256, summaries=False, compilation_cache="",
        frequency=8,
    ))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    assert res["test_accuracy"] > 0.2


def test_pp_1f1b_validation():
    """run() rejects the unsupported 1f1b combos through the shared
    matrix (config.validate_pipeline_config — the full matrix is
    pinned stack-free in test_cli); r8: 1f1b x virtual_stages>1 is
    interleaved-1F1B support now, NOT a rejection."""
    from distributed_tensorflow_example_tpu.config import (
        validate_pipeline_config)
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="pipeline_parallel > 1"):
        run(Config(model="transformer", pp_schedule="1f1b"))
    # the lifted r8 rejection: this exact combination used to raise
    # "requires --virtual_stages=1" — it must validate cleanly now
    validate_pipeline_config(
        Config(model="transformer", pipeline_parallel=2,
               num_blocks=4, virtual_stages=2, microbatches=4,
               pp_schedule="1f1b"))
    with pytest.raises(ValueError, match="balance loss"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, num_experts=4, moe_aux_weight=0.01,
                   pp_schedule="1f1b"))
    with pytest.raises(ValueError, match="sequence/expert"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, sequence_parallel=2,
                   pp_schedule="1f1b"))
    with pytest.raises(ValueError, match="grad_accum"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, grad_accum=2, pp_schedule="1f1b"))
    with pytest.raises(ValueError, match="rematerializes per slot"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=2, remat=True, pp_schedule="1f1b"))
    # interleaved divisibility holds under 1f1b too
    with pytest.raises(ValueError, match="divisible by pipeline_parallel"):
        run(Config(model="transformer", pipeline_parallel=2,
                   num_blocks=4, virtual_stages=2, microbatches=3,
                   pp_schedule="1f1b"))


@needs_stack
@pytest.mark.parametrize("p,virtual,microbatches,dp", [
    (2, 2, 4, 2),    # the acceptance shape: v=2 on 2 stages
    (2, 4, 4, 2),    # deeper interleave, v=4 chunks of 1 block
    (4, 2, 8, 1),    # deep pipeline x interleave (warmup/steady/drain)
], ids=["p2v2", "p2v4", "p4v2"])
def test_pp_interleaved_1f1b_matches_gpipe_and_single_device(
        devices8, p, virtual, microbatches, dp):
    """Interleaved-1F1B (ISSUE 8 tentpole): the fused-tick schedule at
    virtual > 1 — tick table from parallel/pp_schedule, async
    stage-hop start/done pairs, full-circle chunk-wrap ppermutes —
    must produce the SAME step as the gpipe schedule at the identical
    (virtual, microbatches) AND as one device: the schedule changes
    tick order and memory liveness, never math."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import (
        create_train_state)

    nb = p * virtual
    spec = _spec(num_blocks=nb)
    opt = make_optimizer(Config(model="transformer", learning_rate=0.01,
                                num_blocks=nb))
    rng = np.random.RandomState(31)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    cfg1 = Config(model="transformer", learning_rate=0.01,
                  num_blocks=nb)
    p1, c1, _a1 = _one_device_step(spec, opt, cfg1, x, y, devices8)

    def one(schedule):
        cfg = Config(model="transformer", learning_rate=0.01,
                     num_blocks=nb, pipeline_parallel=p,
                     microbatches=microbatches, virtual_stages=virtual,
                     pp_schedule=schedule)
        opt_ = make_optimizer(cfg)
        meshp = mesh_lib.build_stage_mesh(dp, p,
                                          devices=devices8[:dp * p])
        st = create_train_state(jax.random.PRNGKey(1), spec, opt_)
        st = tfm.pipeline_train_state(spec, opt_, st, p, virtual)
        st = mesh_lib.place_state(
            st, meshp,
            mesh_lib.pipeline_state_pspecs(spec, opt_,
                                           mesh_lib.STAGE_AXIS))
        stepp = step_lib.build_train_step(cfg, meshp, spec, opt_)
        newp, cp, _ = stepp(st, x, y)
        un = tfm.pipeline_unstack_params(
            spec, jax.tree.map(np.asarray, newp.params),
            n_stages=p, virtual=virtual)
        return un, float(cp)

    pg, cg = one("gpipe")
    pf, cf = one("1f1b")
    assert abs(c1 - cf) < 2e-5
    assert abs(cg - cf) < 1e-5
    for k in p1:
        np.testing.assert_allclose(pf[k], p1[k], rtol=3e-5, atol=3e-6,
                                   err_msg=f"vs single device: {k}")
        np.testing.assert_allclose(pf[k], pg[k], rtol=2e-5, atol=2e-6,
                                   err_msg=f"vs gpipe: {k}")


@needs_stack
def test_pp_interleaved_1f1b_dropout_matches_gpipe(devices8):
    """Dropout under interleaved-1F1B: the backward sub-slot re-derives
    each microbatch's fold_in rng bit-identically and chunk block
    indices salt exactly like apply_pipeline's stacked positions — the
    two schedules must produce the SAME step from the same state."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import (
        create_train_state)

    spec = _spec(num_blocks=4, dropout_rate=0.2)
    rng = np.random.RandomState(37)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]

    def one(schedule):
        cfg = Config(model="transformer", learning_rate=0.01,
                     num_blocks=4, dropout_rate=0.2,
                     pipeline_parallel=2, microbatches=4,
                     virtual_stages=2, pp_schedule=schedule)
        opt = make_optimizer(cfg)
        meshp = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
        st = create_train_state(jax.random.PRNGKey(1), spec, opt)
        st = tfm.pipeline_train_state(spec, opt, st, 2, 2)
        st = mesh_lib.place_state(
            st, meshp,
            mesh_lib.pipeline_state_pspecs(spec, opt,
                                           mesh_lib.STAGE_AXIS))
        stepp = step_lib.build_train_step(cfg, meshp, spec, opt)
        newp, cp, _ = stepp(st, x, y)
        return jax.tree.map(np.asarray, newp.params), float(cp)

    pg, cg = one("gpipe")
    pf, cf = one("1f1b")
    assert abs(cg - cf) < 1e-5
    for k in pg:
        np.testing.assert_allclose(pf[k], pg[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


@needs_stack
def test_pp_interleaved_1f1b_ckpt_roundtrip(devices8, tmp_path):
    """Checkpoint save/restore round-trip across the (stages, virtual)
    layout under the interleaved-1F1B schedule: a 1-epoch run saves
    the stacked state, the resume continues it at the same layout, and
    a layout change on resume stays rejected."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        model="transformer", pipeline_parallel=2, num_blocks=4,
        data_parallel=4, microbatches=2, pp_schedule="1f1b",
        batch_size=32, learning_rate=0.003, optimizer="adam",
        dataset="synthetic", synthetic_train_size=128,
        synthetic_test_size=64, summaries=False, compilation_cache="",
        frequency=4, checkpoint_dir=str(tmp_path),
    )
    res = run(Config(training_epochs=1, virtual_stages=2, **kw))
    assert res["devices"] == 8
    assert res["steps"] == 4
    res2 = run(Config(training_epochs=2, resume=True, virtual_stages=2,
                      **kw))
    assert res2["steps"] == 8
    assert np.isfinite(res2["final_cost"])
    with pytest.raises(ValueError, match="pinned to that layout"):
        run(Config(training_epochs=3, resume=True, virtual_stages=1,
                   **kw))


# ---- DP-sharded decode (r5, VERDICT r4 next #8) ----


def test_generate_dp_matches_host(devices8):
    """generate_dp (prompt batch sharded over 'data') must reproduce
    the host generate exactly under greedy decoding — including a
    batch that does not divide the data axis. The contract is
    SYMMETRIC across process counts (r5 ADVICE): always the padded
    global array + the valid count, with dp_samples_host doing the
    slice."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib

    spec = _lm_spec()
    params = tfm.init(jax.random.PRNGKey(3), spec)
    rng = np.random.RandomState(41)
    prompts = jnp.asarray(rng.randint(0, 16, size=(6, 8)), jnp.int32)

    host = np.asarray(tfm.generate(spec, params, prompts, rng=None,
                                   temperature=0.0))
    mesh = mesh_lib.build_mesh(4, 1, devices=devices8[:4])
    padded, n = tfm.generate_dp(spec, params, prompts, mesh,
                                rng=None, temperature=0.0)
    assert n == 6
    assert padded.shape[0] == 8  # 6 padded up to the data axis (4)
    dp_out = tfm.dp_samples_host(padded, n)
    np.testing.assert_array_equal(dp_out, host)


def test_generate_dp_tp_matches_host(devices8):
    """DP x TP decode: batch shards over 'data' while each shard's
    heads split over 'model' — still exactly the host greedy decode."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib

    spec = _lm_spec()
    params = tfm.init(jax.random.PRNGKey(5), spec)
    rng = np.random.RandomState(43)
    prompts = jnp.asarray(rng.randint(0, 16, size=(4, 8)), jnp.int32)

    host = np.asarray(tfm.generate(spec, params, prompts, rng=None,
                                   temperature=0.0))
    mesh = mesh_lib.build_mesh(2, 2, devices=devices8[:4])
    pspecs = tfm.param_pspecs(spec, model_axis=mesh_lib.MODEL_AXIS)
    from jax.sharding import NamedSharding

    placed = {k: jax.device_put(v, NamedSharding(mesh, pspecs[k]))
              for k, v in params.items()}
    padded, n = tfm.generate_dp(
        spec, placed, prompts, mesh, model_axis=mesh_lib.MODEL_AXIS,
        rng=None, temperature=0.0)
    dp_out = tfm.dp_samples_host(padded, n)
    np.testing.assert_array_equal(dp_out, host)


def test_generate_dp_sampled_finite(devices8):
    """Sampled DP decode: per-shard keys fold in the data coordinate,
    tokens stay inside the vocabulary."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib

    spec = _lm_spec()
    params = tfm.init(jax.random.PRNGKey(7), spec)
    rng = np.random.RandomState(47)
    prompts = jnp.asarray(rng.randint(0, 16, size=(8, 8)), jnp.int32)
    mesh = mesh_lib.build_mesh(4, 1, devices=devices8[:4])
    out = tfm.dp_samples_host(*tfm.generate_dp(
        spec, params, prompts, mesh, rng=jax.random.PRNGKey(9),
        temperature=1.0))
    assert out.shape == (8, spec.seq_len)
    assert out.min() >= 0 and out.max() < spec.vocab_size
    # prompt teacher-forced
    np.testing.assert_array_equal(out[:, :8], np.asarray(prompts))
