"""FSDP (ZeRO-3) sharding tests on the 8-virtual-device mesh:
the sharded-state step must reproduce the single-device step, each
device must hold only 1/dp of the state, and the host-side layout
round-trip must be exact (checkpoints keep the unsharded layout)."""

import jax
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models.mlp import MLPSpec
from distributed_tensorflow_example_tpu.parallel import fsdp as fsdp_lib
from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
from distributed_tensorflow_example_tpu.parallel import step as step_lib
from distributed_tensorflow_example_tpu.train.optim import make_optimizer
from distributed_tensorflow_example_tpu.train.state import create_train_state

SPEC = MLPSpec(input_size=16, hidden_sizes=(8,), num_classes=4)
DEEP = MLPSpec(input_size=16, hidden_sizes=(12, 8), num_classes=4,
               activation="relu")


def _data(batch, spec, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(batch, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, batch)
    ]
    return x, y


def _run_single(cfg, spec, n_steps=3, seed=0):
    mesh = mesh_lib.build_mesh(1, 1)
    opt = make_optimizer(cfg)
    state = create_train_state(jax.random.PRNGKey(1), spec, opt)
    state = mesh_lib.place_state(state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
    step = step_lib.build_train_step(cfg, mesh, spec, opt)
    for i in range(n_steps):
        x, y = _data(96, spec, seed=seed + i)
        state, cost, acc = step(state, x, y)
    return jax.device_get(state.params), float(cost)


def _run_fsdp(cfg, spec, dp, n_steps=3, seed=0):
    mesh = mesh_lib.build_mesh(dp, 1)
    opt = make_optimizer(cfg)
    full = create_train_state(jax.random.PRNGKey(1), spec, opt)
    full_host = jax.tree.map(np.asarray, full)
    state = fsdp_lib.shard_state_host(full_host, dp)
    state = mesh_lib.place_state(state, mesh, fsdp_lib.fsdp_specs(state))
    step = fsdp_lib.build_fsdp_train_step(cfg, mesh, spec, opt, full_host)
    for i in range(n_steps):
        x, y = _data(96, spec, seed=seed + i)
        state, cost, acc = step(state, x, y)
    gather = fsdp_lib.build_gather_params(mesh, full_host)
    return jax.device_get(gather(state)), float(cost), state


@pytest.mark.parametrize("opt_name", ["sgd", "momentum", "adam"])
def test_fsdp8_equals_single_device(devices8, opt_name):
    """8-way-sharded params/opt-state step == 1-device step: the
    all-gather -> local fwd/bwd -> reduce-scatter -> shard update cycle
    is the same math as psum sync DP."""
    cfg = Config(optimizer=opt_name, learning_rate=0.05, grad_reduce="mean")
    p1, c1 = _run_single(cfg, SPEC)
    p8, c8, _ = _run_fsdp(cfg, SPEC, 8)
    assert abs(c1 - c8) < 1e-5
    for k in p1:
        np.testing.assert_allclose(p1[k], p8[k], rtol=2e-5, atol=2e-6, err_msg=k)


def test_fsdp_deep_model_adam(devices8):
    cfg = Config(optimizer="adam", learning_rate=0.01, activation="relu")
    p1, _ = _run_single(cfg, DEEP)
    p8, _, _ = _run_fsdp(cfg, DEEP, 8)
    for k in p1:
        np.testing.assert_allclose(p1[k], p8[k], rtol=2e-5, atol=2e-6, err_msg=k)


def test_fsdp_composes_with_pallas_and_remat(devices8):
    """--fsdp --pallas --remat: the gathered params feed the fused
    forward unchanged; updates still match the single-device step."""
    cfg = Config(learning_rate=0.05, pallas=True, remat=True)
    p1, _ = _run_single(Config(learning_rate=0.05), SPEC)
    p8, _, _ = _run_fsdp(cfg, SPEC, 8)
    for k in p1:
        np.testing.assert_allclose(p1[k], p8[k], rtol=2e-5, atol=2e-6, err_msg=k)


def test_fsdp_state_is_actually_sharded(devices8):
    """Each device holds exactly one [1, chunk] block of every float
    leaf — 1/dp of the model + optimizer memory, the ZeRO-3 claim."""
    cfg = Config(optimizer="adam", learning_rate=0.01)
    _, _, state = _run_fsdp(cfg, SPEC, 8, n_steps=1)
    leaves = [l for l in jax.tree.leaves(state.params)]
    leaves += [
        l for l in jax.tree.leaves(state.opt_state)
        if hasattr(l, "ndim") and l.ndim >= 1
    ]
    assert leaves, "expected sharded leaves"
    for leaf in leaves:
        assert leaf.shape[0] == 8, leaf.shape
        shard = leaf.addressable_shards[0]
        assert shard.data.shape == (1, leaf.shape[1]), (
            f"device shard {shard.data.shape} is not 1/8 of {leaf.shape}"
        )


def test_shard_unshard_roundtrip_exact():
    """Host-side layout conversion is lossless for every leaf kind
    (weights, biases, Adam's mu/nu and integer count), including shapes
    that do not divide dp (784, 100, 10 vs dp=8)."""
    spec = MLPSpec()  # the reference 784-100-10 — nothing divides 8
    cfg = Config(optimizer="adam")
    opt = make_optimizer(cfg)
    full = jax.tree.map(
        np.asarray, create_train_state(jax.random.PRNGKey(1), spec, opt)
    )
    sharded = fsdp_lib.shard_state_host(full, 8)
    back = fsdp_lib.unshard_state_host(sharded, full)
    flat_a = jax.tree_util.tree_leaves_with_path(full)
    flat_b = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(back)
    )
    for path, leaf in flat_a:
        key = jax.tree_util.keystr(path)
        np.testing.assert_array_equal(leaf, flat_b[key], err_msg=key)


def _run_fsdp_tp(cfg, spec, dp, mp, n_steps=3, seed=0):
    """The 2D FSDP x TP step: leaves Megatron-shard over 'model', the
    TP shards flatten over 'data' ([mp, dp, chunk])."""
    mesh = mesh_lib.build_mesh(dp, mp)
    opt = make_optimizer(cfg)
    full = create_train_state(jax.random.PRNGKey(1), spec, opt)
    full_host = jax.tree.map(np.asarray, full)
    tp_specs = mesh_lib.state_pspecs(spec, opt, mp)
    state = fsdp_lib.shard_state_host(full_host, dp, mp, tp_specs)
    state = mesh_lib.place_state(state, mesh,
                                 fsdp_lib.fsdp_specs(state, mp))
    step = fsdp_lib.build_fsdp_train_step(cfg, mesh, spec, opt, full_host)
    for i in range(n_steps):
        x, y = _data(96, spec, seed=seed + i)
        state, cost, acc = step(state, x, y)
    gather = fsdp_lib.build_gather_params(mesh, full_host, spec)
    return jax.device_get(gather(state)), float(cost), state


@pytest.mark.parametrize("opt_name,grad_clip", [
    ("sgd", 0.0),     # raw-gradient exactness (Adam's normalization
                      # would mask a uniform per-leaf scale error —
                      # exactly the bug class this composition risks)
    ("adam", 0.0),
    ("adam", 0.05),   # the sharding-exact global-norm clip binding
], ids=["sgd", "adam", "adam-clip"])
def test_fsdp_tp_mlp_equals_single_device(devices8, opt_name, grad_clip):
    """DP4 x TP2 FSDP (VERDICT r3 next #5): col/row Megatron styles on
    the MLP composed with the flat ZeRO-3 partitioning — including the
    sharding-exact global-norm clip (TP-sharded leaves psum over both
    axes, TP-replicated ones over 'data' only). Sigmoid, not relu: a
    relu gate sitting exactly on 0 can flip under the TP psum's fp
    reassociation, turning ~1e-7 forward noise into an O(lr) update
    difference — a float artifact, not a layout one."""
    spec = MLPSpec(input_size=16, hidden_sizes=(12, 8), num_classes=4)
    cfg = Config(optimizer=opt_name, learning_rate=0.01,
                 grad_clip=grad_clip)
    p1, c1 = _run_single(cfg, spec)
    p4, c4, _ = _run_fsdp_tp(cfg, spec, 4, 2)
    # TP psum reassociation: agreement to fp32 noise, not bitwise
    assert abs(c1 - c4) < 5e-5
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_fsdp_tp_transformer_equals_single_device(devices8):
    """DP2 x TP2 FSDP on the transformer family: gathered TP-local
    shards feed the Megatron forward (head/hidden psums), gradients
    reduce-scatter over 'data' only."""
    from distributed_tensorflow_example_tpu.models import (
        transformer as tfm)

    spec = tfm.TransformerSpec(input_size=64, seq_len=8, d_model=16,
                               n_heads=2, num_blocks=2, d_ff=32,
                               num_classes=4)
    # sgd, not adam: the K-bias gradient is mathematically zero
    # (per-row softmax shift invariance), so Adam's normalization
    # would amplify its fp-noise into lr-scale random disagreement
    cfg = Config(model="transformer", optimizer="sgd",
                 learning_rate=0.05)
    p1, c1 = _run_single(cfg, spec)
    p4, c4, state = _run_fsdp_tp(cfg, spec, 2, 2)
    assert abs(c1 - c4) < 1e-5
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    # each leaf really is [mp, dp, chunk] sharded over both axes
    leaf = state.params["L0_Wqkv"]
    assert leaf.shape[:2] == (2, 2)
    db = leaf.sharding.device_set
    assert len(db) == 4


def test_fsdp_tp_shard_unshard_roundtrip_exact():
    """Host-side FSDP x TP layout conversion is lossless, including
    TP-replicated leaves and Adam's integer count."""
    from distributed_tensorflow_example_tpu.models import (
        transformer as tfm)

    spec = tfm.TransformerSpec(input_size=64, seq_len=8, d_model=16,
                               n_heads=2, num_blocks=1, d_ff=32,
                               num_classes=4)
    cfg = Config(model="transformer", optimizer="adam")
    opt = make_optimizer(cfg)
    full = jax.tree.map(
        np.asarray, create_train_state(jax.random.PRNGKey(1), spec, opt))
    tp_specs = mesh_lib.state_pspecs(spec, opt, 2)
    sharded = fsdp_lib.shard_state_host(full, 4, 2, tp_specs)
    back = fsdp_lib.unshard_state_host(sharded, full, 2, tp_specs)
    flat_a = jax.tree_util.tree_leaves_with_path(full)
    flat_b = dict(
        (jax.tree_util.keystr(p), l)
        for p, l in jax.tree_util.tree_leaves_with_path(back)
    )
    for path, leaf in flat_a:
        key = jax.tree_util.keystr(path)
        np.testing.assert_array_equal(leaf, flat_b[key], err_msg=key)


def test_fsdp_tp_driver_end_to_end(devices8, tmp_path):
    """--fsdp --model_parallel=2 through the full driver (the gate
    VERDICT r3 weak #4 called out is gone): trains on the scan path,
    evals, checkpoints unsharded, resumes."""
    from distributed_tensorflow_example_tpu.train.loop import run

    kw = dict(
        model="transformer", fsdp=True, model_parallel=2,
        data_parallel=4, d_model=32, n_heads=2, num_blocks=2, d_ff=64,
        batch_size=64, learning_rate=0.003,
        optimizer="adam", dataset="synthetic",
        synthetic_train_size=512, synthetic_test_size=128,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=str(tmp_path),
    )
    res = run(Config(training_epochs=1, **kw))
    assert res["devices"] == 8
    assert np.isfinite(res["final_cost"])
    res2 = run(Config(resume=True, training_epochs=2, **kw))
    assert res2["steps"] == 16


@pytest.mark.parametrize("ckpt_every", [0, 5],
                         ids=["whole_run", "per_epoch"])
def test_fsdp_end_to_end_run(devices8, monkeypatch, tmp_path, ckpt_every):
    """loop.run --fsdp on both fast paths (checkpoint_every=0 takes the
    whole-run program with the overlapped eval dispatch; >0 takes the
    per-epoch runner): trains, evals, checkpoints in the portable
    unsharded layout, and resumes."""
    import distributed_tensorflow_example_tpu.train.loop as loop_mod
    from distributed_tensorflow_example_tpu.data import mnist as M
    from distributed_tensorflow_example_tpu.utils import checkpoint as ckpt_lib

    ds = M.Dataset(
        train=M.synthesize_split(800, seed=1),
        validation=M.synthesize_split(80, seed=2),
        test=M.synthesize_split(200, seed=3),
        source="synthetic",
    )
    monkeypatch.setattr(loop_mod, "load_datasets", lambda *a, **k: ds)
    cfg = Config(
        training_epochs=1, batch_size=80, learning_rate=0.05,
        optimizer="adam", activation="relu", hidden_sizes=(32,),
        fsdp=True, summaries=False, checkpoint_dir=str(tmp_path),
        checkpoint_every=ckpt_every,
        logs_path=str(tmp_path / "logs"),
    )
    res = loop_mod.run(cfg)
    assert res["fast_loop"] is True  # FSDP rides the scan paths
    assert np.isfinite(res["final_cost"])
    assert res["steps"] == 10

    # checkpoint leaves carry the unsharded reference shapes
    path = ckpt_lib.latest_checkpoint(str(tmp_path))
    with np.load(path) as z:
        assert z[".params/W1"].shape == (784, 32)
        assert z[".opt_state/mu/W1"].shape == (784, 32)

    res2 = loop_mod.run(cfg.replace(resume=True, training_epochs=2))
    assert res2["steps"] == 20


def test_fsdp_fast_runner_equals_sync_fast_runner(devices8):
    """The FSDP whole-run scan program must produce the same parameter
    trajectory as the plain sync whole-run program (identical shuffle
    keying and data layout; only the state partitioning differs)."""
    from distributed_tensorflow_example_tpu.parallel import epoch as epoch_lib

    spec = SPEC
    cfg = Config(learning_rate=0.05, optimizer="adam")
    mesh = mesh_lib.build_mesh(8, 1)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(0)
    n = 8 * 6 * 4
    imgs = (rng.randint(0, 256, size=(n, spec.input_size)) / 255.0).astype(
        np.float32
    )
    lbls = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, n)
    ]
    img_d, lbl_d, spe = epoch_lib.shard_dataset(mesh, imgs, lbls, 8 * 4)
    key = jax.random.PRNGKey(7)

    # sync path
    state_s = create_train_state(jax.random.PRNGKey(1), spec, opt)
    state_s = mesh_lib.place_state(
        state_s, mesh, mesh_lib.state_pspecs(spec, opt, 1)
    )
    run_s = epoch_lib.build_run_to_completion(cfg, mesh, spec, opt, spe, 2)
    state_s, costs_s, _ = run_s(state_s, img_d, lbl_d, key)

    # fsdp path, same data/key
    full = jax.tree.map(
        np.asarray, create_train_state(jax.random.PRNGKey(1), spec, opt)
    )
    state_f = fsdp_lib.shard_state_host(full, 8)
    state_f = mesh_lib.place_state(state_f, mesh, fsdp_lib.fsdp_specs(full))
    run_f = epoch_lib.build_fsdp_run_to_completion(
        cfg, mesh, spec, opt, full, spe, 2
    )
    state_f, costs_f, _ = run_f(state_f, img_d, lbl_d, key)

    np.testing.assert_allclose(
        np.asarray(costs_f), np.asarray(costs_s), rtol=1e-5, atol=1e-6
    )
    gather = fsdp_lib.build_gather_params(mesh, full)
    p_f = jax.device_get(gather(state_f))
    p_s = jax.device_get(state_s.params)
    for k in p_s:
        np.testing.assert_allclose(p_f[k], p_s[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_fsdp_rejects_async(devices8):
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="fsdp"):
        run(Config(fsdp=True, sync_period=4))


# ---------------------------------------------------------------------------
# ZeRO-1 (--zero_opt, parallel/zero.py): optimizer-state-only sharding
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("opt_name", ["sgd", "momentum", "adam"])
def test_zero1_dp_equals_single_device(devices8, opt_name):
    """ZeRO-1 (r5, VERDICT r4 next #3): slots flat-sharded 1/dp over
    'data', params replicated — the chunked update + param all-gather
    must reproduce the single-device step, and each device must hold
    only its chunk of every slot."""
    from distributed_tensorflow_example_tpu.parallel import zero as zero_lib

    cfg = Config(optimizer=opt_name, learning_rate=0.05,
                 grad_reduce="mean", zero_opt=True)
    p1, c1 = _run_single(cfg.replace(zero_opt=False), SPEC)

    mesh = mesh_lib.build_mesh(8, 1)
    opt = make_optimizer(cfg)
    state = create_train_state(jax.random.PRNGKey(1), SPEC, opt)
    sspecs = mesh_lib.state_pspecs(SPEC, opt, 1)
    z_state, z_specs = zero_lib.zero_opt_state(
        opt, state.params, sspecs.params, mesh, 8)
    from distributed_tensorflow_example_tpu.train.state import TrainState
    from jax.sharding import PartitionSpec as P

    state = TrainState(step=state.step, params=state.params,
                       opt_state=z_state)
    sspecs = TrainState(step=P(), params=sspecs.params,
                        opt_state=z_specs)
    state = mesh_lib.place_state(state, mesh, sspecs)
    step = step_lib.build_train_step(cfg, mesh, SPEC, opt)
    for i in range(3):
        x, y = _data(96, SPEC, seed=i)
        state, cost, _ = step(state, x, y)
    p8 = jax.device_get(state.params)
    assert abs(c1 - float(cost)) < 1e-5
    for k in p1:
        np.testing.assert_allclose(p1[k], p8[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    if opt_name != "sgd":
        # every slot leaf is [dp, chunk] with each device holding one
        # [1, chunk] block
        slots = (state.opt_state["m"] if opt_name == "momentum"
                 else state.opt_state["mu"])
        for k, leaf in slots.items():
            assert leaf.shape[0] == 8, (k, leaf.shape)
            shard = leaf.addressable_shards[0]
            assert shard.data.shape[0] == 1, (k, shard.data.shape)


def test_zero1_pp_equals_plain_pp_step(devices8):
    """ZeRO x PP (the r4 verdict's missing recipe): PP2 x DP2 with
    Adam slots flat-sharded over 'data' while the stacked block params
    shard over 'stage'. The chunked update is ELEMENTWISE-identical
    math to the plain replicated-slot update, so against the same-mesh
    plain PP step (identical grads — Adam's sign-like first step would
    amplify mere reduction-order noise against a 1-device baseline)
    the params must match to fp-noise tightness."""
    from distributed_tensorflow_example_tpu.models import transformer as tfm
    from distributed_tensorflow_example_tpu.parallel import zero as zero_lib
    from distributed_tensorflow_example_tpu.train.state import TrainState
    from jax.sharding import PartitionSpec as P

    spec = tfm.TransformerSpec(input_size=784, num_classes=10,
                               seq_len=28, d_model=32, n_heads=2,
                               num_blocks=2, d_ff=64)
    cfg = Config(model="transformer", optimizer="adam",
                 learning_rate=0.01, pipeline_parallel=2, num_blocks=2,
                 microbatches=2, zero_opt=True)
    opt = make_optimizer(cfg)
    rng = np.random.RandomState(53)
    x = rng.rand(8, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8)]
    mesh = mesh_lib.build_stage_mesh(2, 2, devices=devices8[:4])
    sspecs0 = mesh_lib.pipeline_state_pspecs(spec, opt,
                                             mesh_lib.STAGE_AXIS)

    # plain PP baseline: replicated slots on the SAME mesh
    st0 = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st0 = tfm.pipeline_train_state(spec, opt, st0, 2, 1)
    stacked_host = jax.tree.map(np.asarray, st0.params)
    st0 = mesh_lib.place_state(st0, mesh, sspecs0)
    step0 = step_lib.build_train_step(cfg.replace(zero_opt=False),
                                      mesh, spec, opt)
    new0, c0, _ = step0(st0, x, y)
    p0 = jax.tree.map(np.asarray, new0.params)

    # ZeRO-1: flat dp-sharded slots
    st = create_train_state(jax.random.PRNGKey(1), spec, opt)
    st = tfm.pipeline_train_state(spec, opt, st, 2, 1)
    z_state, z_specs = zero_lib.zero_opt_state(
        opt, st.params, sspecs0.params, mesh, 2)
    st = TrainState(step=st.step, params=st.params, opt_state=z_state)
    sspecs = TrainState(step=P(), params=sspecs0.params,
                        opt_state=z_specs)
    st = mesh_lib.place_state(st, mesh, sspecs)
    stepp = step_lib.build_train_step(cfg, mesh, spec, opt)
    newp, cp, _ = stepp(st, x, y)
    pz = jax.tree.map(np.asarray, newp.params)

    assert abs(c0 - float(cp)) < 1e-7
    for k in p0:
        np.testing.assert_allclose(pz[k], p0[k], rtol=1e-7, atol=1e-8,
                                   err_msg=k)
        # and the step actually moved the params
        assert not np.array_equal(pz[k], stacked_host[k]), k
    # stacked slot leaves are [p, dp, chunk] sharded ('stage','data')
    mu = newp.opt_state["mu"]["blk_Wqkv"]
    assert mu.shape[:2] == (2, 2), mu.shape
    assert mu.addressable_shards[0].data.shape[:2] == (1, 1)


def test_zero1_driver_resume(devices8, tmp_path):
    """--zero_opt through the full driver with checkpoint + resume
    (same dp restores; the dp-shaped chunking is validated)."""
    from distributed_tensorflow_example_tpu.train.loop import run

    ckpt = str(tmp_path / "zck")
    kw = dict(
        model="transformer", optimizer="adam", learning_rate=0.003,
        pipeline_parallel=2, num_blocks=2, data_parallel=4,
        microbatches=2, zero_opt=True, batch_size=32,
        synthetic_train_size=128, synthetic_test_size=32,
        summaries=False, compilation_cache="", frequency=4,
        checkpoint_dir=ckpt, checkpoint_every=2,
    )
    res = run(Config(training_epochs=1, **kw))
    assert np.isfinite(res["final_cost"])
    res2 = run(Config(training_epochs=2, resume=True, **kw))
    assert res2["epochs_completed"] == 2
    with pytest.raises(ValueError, match="zero_dp"):
        run(Config(training_epochs=2, resume=True,
                   **{**kw, "data_parallel": 2}))


def test_zero_rejects_fsdp_and_async():
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="redundant"):
        run(Config(zero_opt=True, fsdp=True))
    with pytest.raises(ValueError, match="synchronous"):
        run(Config(zero_opt=True, sync_period=3))


def test_remat_same_updates(devices8):
    """--remat recomputes activations but must change nothing
    numerically (one step, deep ReLU model, Adam)."""
    cfg = Config(optimizer="adam", learning_rate=0.01, activation="relu")
    p_plain, _ = _run_single(cfg, DEEP, n_steps=2)
    p_remat, _ = _run_single(cfg.replace(remat=True), DEEP, n_steps=2)
    for k in p_plain:
        np.testing.assert_array_equal(p_plain[k], p_remat[k], err_msg=k)
