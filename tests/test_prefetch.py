"""Input pipeline: host prefetcher (same batches, same order, errors
propagate, post-close iteration fails fast), the persistent
EpochPrefetcher (one producer across epochs, epoch-keyed rewind) and
the DevicePrefetcher commit pipeline (depth bounds, error propagation,
early-exit close, epoch-persistent rewind) — all pure python. The
stack-gated test at the bottom pins the acceptance invariant: the
device-prefetched path is bit-exact with the synchronous-commit path.
"""

import itertools
import time

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.data import (
    DevicePrefetcher, EpochIterator, EpochPrefetcher, Prefetcher)
from distributed_tensorflow_example_tpu.data import mnist as M

from conftest import needs_stack  # noqa: E402


# --- Prefetcher (host stage) ----------------------------------------------


def test_prefetcher_preserves_batches():
    split = M.synthesize_split(100, seed=3)
    a = list(EpochIterator(split, batch_size=10, seed=1, shard=False).epoch())
    b = list(Prefetcher(EpochIterator(split, batch_size=10, seed=1, shard=False).epoch()))
    assert len(a) == len(b) == 10
    for (ax, ay), (bx, by) in zip(a, b):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetcher_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")

    p = Prefetcher(gen())
    it = iter(p)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetcher_close_unblocks_producer():
    produced = []

    def gen():
        for i in itertools.count():
            produced.append(i)
            yield i

    p = Prefetcher(gen(), depth=2)
    it = iter(p)
    assert next(it) == 0
    p.close()
    p._thread.join(timeout=5)
    assert not p._thread.is_alive()
    # producer stopped promptly: queue depth 2 + in-flight item bound
    assert len(produced) < 10


def test_prefetcher_closed_iteration_raises():
    """Regression: close() drains the queue — including the end
    sentinel — so iterating a closed prefetcher used to hang forever
    on an empty queue. It must raise immediately instead."""
    p = Prefetcher(iter([1, 2, 3]))
    p.close()
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="closed"):
        iter(p)
    assert time.perf_counter() - t0 < 1.0  # fails fast, no hang

    # exhausting an iteration auto-closes (the finally); a second
    # iteration of the spent prefetcher must raise too, not hang
    p2 = Prefetcher(iter([1]))
    assert list(p2) == [1]
    with pytest.raises(RuntimeError, match="closed"):
        iter(p2)


def test_prefetcher_close_mid_iteration_raises_not_hangs():
    p = Prefetcher(iter(range(100)), depth=1)
    it = iter(p)
    assert next(it) == 0
    p.close()
    with pytest.raises(RuntimeError, match="closed"):
        # the queue was drained by close(): without the check this
        # next() would block forever waiting for a sentinel
        next(it)


# --- EpochPrefetcher (persistent producer, epoch-keyed rewind) ------------


def _epoch_fn(e):
    return iter([(e, i) for i in range(4)])


def test_epoch_prefetcher_one_producer_many_epochs():
    ep = EpochPrefetcher(_epoch_fn, range(3))
    thread = ep._thread
    for e in range(3):
        assert list(ep.epoch(e)) == [(e, i) for i in range(4)]
        assert ep._thread is thread  # the SAME producer, no respawn
    ep.close()


def test_epoch_prefetcher_matches_epoch_iterator():
    """The persistent producer yields exactly what per-epoch
    EpochIterator.epoch(e) calls would — epoch-keyed shuffles intact."""
    split = M.synthesize_split(40, seed=7)

    def mk():
        return EpochIterator(split, batch_size=10, seed=1, shard=False)

    ep = EpochPrefetcher(mk().epoch, range(2))
    ref = mk()
    for e in range(2):
        got = list(ep.epoch(e))
        want = list(ref.epoch(e))
        assert len(got) == len(want) == 4
        for (gx, gy), (wx, wy) in zip(got, want):
            np.testing.assert_array_equal(gx, wx)
            np.testing.assert_array_equal(gy, wy)
    ep.close()


def test_epoch_prefetcher_rewind_skips_abandoned_epoch():
    ep = EpochPrefetcher(_epoch_fn, range(5, 8))
    it = ep.epoch(5)
    assert next(it) == (5, 0)  # abandon epoch 5 mid-way
    assert list(ep.epoch(6)) == [(6, i) for i in range(4)]
    # the stream is forward-only: a consumed epoch cannot come back
    with pytest.raises(RuntimeError, match="forward-only"):
        list(ep.epoch(5))
    # an epoch outside the sequence is a hard error, not a hang
    with pytest.raises(RuntimeError, match="not in this prefetcher"):
        list(ep.epoch(42))
    ep.close()


def test_epoch_prefetcher_direct_iteration_rejected():
    """Direct iteration would interleave internal epoch markers with
    batches — the per-epoch surface is .epoch(e)."""
    ep = EpochPrefetcher(_epoch_fn, range(2))
    with pytest.raises(TypeError, match="epoch"):
        iter(ep)
    assert list(ep.epoch(0)) == [(0, i) for i in range(4)]
    ep.close()


def test_epoch_prefetcher_rejects_rerequest_of_started_epoch():
    """A partially-consumed epoch can never be handed out again: the
    remainder would be a silently truncated epoch, not 'exactly epoch
    e's batches'."""
    ep = EpochPrefetcher(_epoch_fn, range(2))
    it = ep.epoch(0)
    assert next(it) == (0, 0)
    with pytest.raises(RuntimeError, match="forward-only"):
        ep.epoch(0)
    assert list(ep.epoch(1)) == [(1, i) for i in range(4)]
    ep.close()


def test_epoch_prefetcher_propagates_producer_error():
    def bad_epoch(e):
        yield (e, 0)
        if e == 1:
            raise ValueError("gather failed")

    ep = EpochPrefetcher(bad_epoch, range(3))
    assert list(ep.epoch(0)) == [(0, 0)]
    it = ep.epoch(1)
    assert next(it) == (1, 0)
    with pytest.raises(ValueError, match="gather failed"):
        next(it)
    ep.close()


def test_epoch_prefetcher_close_then_epoch_raises():
    ep = EpochPrefetcher(_epoch_fn, range(2))
    assert list(ep.epoch(0)) == [(0, i) for i in range(4)]
    ep.close()
    ep._thread.join(timeout=5)
    assert not ep._thread.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        next(ep.epoch(1))


# --- DevicePrefetcher (commit pipeline) -----------------------------------


class _CountingCommit:
    """Fake commit: tags batches and counts calls (the pure-python
    stand-in for device_put with the step sharding)."""

    def __init__(self):
        self.calls = 0

    def __call__(self, x, y):
        self.calls += 1
        return ("dev", x, y)


def test_device_prefetcher_commits_ahead_within_depth():
    commit = _CountingCommit()
    dp = DevicePrefetcher(commit, depth=3,
                          source=[(i, -i) for i in range(10)])
    consumed = 0
    for item in dp:
        consumed += 1
        # never more than `depth` commits ahead of consumption
        assert commit.calls - consumed <= 3
        assert item == ("dev", consumed - 1, -(consumed - 1))
    assert consumed == 10 and commit.calls == 10


def test_device_prefetcher_depth_validated():
    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher(lambda x, y: (x, y), depth=0)


def test_device_prefetcher_preserves_order_and_values():
    dp = DevicePrefetcher(lambda x, y: (x * 2, y * 2), depth=2,
                          source=[(i, i + 100) for i in range(7)])
    assert list(dp) == [(2 * i, 2 * (i + 100)) for i in range(7)]


def test_device_prefetcher_source_error_after_buffered_items():
    def src():
        yield (0, 0)
        yield (1, 1)
        raise RuntimeError("host gather died")

    dp = DevicePrefetcher(lambda x, y: (x, y), depth=4, source=src())
    it = iter(dp)
    assert next(it) == (0, 0)
    assert next(it) == (1, 1)  # committed batches drain first
    with pytest.raises(RuntimeError, match="host gather died"):
        next(it)


def test_device_prefetcher_commit_error_propagates():
    def bad_commit(x, y):
        if x == 2:
            raise ValueError("transfer failed")
        return (x, y)

    dp = DevicePrefetcher(bad_commit, depth=1, source=[(i, i) for i in range(4)])
    it = iter(dp)
    assert next(it) == (0, 0)
    assert next(it) == (1, 1)
    with pytest.raises(ValueError, match="transfer failed"):
        next(it)


def test_device_prefetcher_keyboard_interrupt_not_deferred():
    """_fill runs on the consumer thread: a KeyboardInterrupt from the
    source must stop the run NOW, not surface `depth` batches later
    disguised as a data-pipeline failure."""
    def src():
        yield (0, 0)
        raise KeyboardInterrupt

    dp = DevicePrefetcher(lambda x, y: (x, y), depth=4, source=src())
    with pytest.raises(KeyboardInterrupt):
        next(iter(dp))  # raised before the buffered batch is served


def test_device_prefetcher_early_exit_close():
    commit = _CountingCommit()
    dp = DevicePrefetcher(commit, depth=2,
                          source=[(i, i) for i in range(100)])
    it = iter(dp)
    next(it)
    dp.close()
    assert dp.closed and len(dp._buf) == 0  # buffers released
    with pytest.raises(RuntimeError, match="closed"):
        iter(dp)
    with pytest.raises(RuntimeError, match="closed"):
        next(it)
    with pytest.raises(RuntimeError, match="closed"):
        dp.rewind([(0, 0)])
    before = commit.calls
    time.sleep(0.01)
    assert commit.calls == before  # nothing commits after close


def test_device_prefetcher_epoch_persistent_rewind():
    """ONE instance spans epochs: rewind() re-arms it on the next
    epoch's source, dropping the old epoch's buffered commits and
    clearing a pending source error."""
    commit = _CountingCommit()
    dp = DevicePrefetcher(commit, depth=3)

    # a fresh instance with no source is simply empty
    assert list(dp) == []

    dp.rewind([(0, i) for i in range(5)])
    it = iter(dp)
    assert next(it) == ("dev", 0, 0)  # epoch 0 abandoned mid-way

    dp.rewind([(1, i) for i in range(3)])
    assert list(dp) == [("dev", 1, i) for i in range(3)]

    # rewind clears a pending error from the previous source
    def bad():
        raise RuntimeError("boom")
        yield  # pragma: no cover

    dp.rewind(bad())
    with pytest.raises(RuntimeError, match="boom"):
        list(dp)
    dp.rewind([(2, 0)])
    assert list(dp) == [("dev", 2, 0)]


def test_device_prefetcher_over_epoch_prefetcher():
    """The composition the train loop runs: EpochPrefetcher feeds a
    persistent DevicePrefetcher, rewound per epoch."""
    commit = _CountingCommit()
    ep = EpochPrefetcher(_epoch_fn, range(2))
    dp = DevicePrefetcher(commit, depth=2)
    out = []
    for e in range(2):
        out.append(list(dp.rewind(ep.epoch(e))))
    dp.close()
    ep.close()
    assert out == [[("dev", e, i) for i in range(4)] for e in range(2)]
    assert commit.calls == 8


# --- acceptance: device-prefetched path == synchronous-commit path --------


@needs_stack
@pytest.mark.parametrize("histograms", [False, True])
def test_device_prefetch_bit_exact_with_blocking_commit(tmp_path,
                                                        histograms):
    """Same seed -> identical final cost/accuracy AND bit-identical
    final params (via the checkpoint) whether batches are committed
    synchronously at dispatch or prefetched to device ahead of
    consumption. Parametrized over the with_norms step variant; the
    anomaly variants share the same feed path (the variants differ
    only in step OUTPUTS, never in how batches arrive)."""
    from distributed_tensorflow_example_tpu.config import Config
    from distributed_tensorflow_example_tpu.train.loop import run
    from distributed_tensorflow_example_tpu.utils import checkpoint as ckpt

    base = Config(batch_size=32, dataset="synthetic",
                  synthetic_train_size=32 * 6, synthetic_test_size=64,
                  training_epochs=2, summaries=histograms,
                  histograms=histograms, log_every=3,
                  fast_loop=False, frequency=1000)
    results, params = {}, {}
    for name, dev in (("blocking", False), ("prefetched", True)):
        cdir = tmp_path / f"ckpt_{name}_{histograms}"
        ldir = tmp_path / f"logs_{name}_{histograms}"
        r = run(base.replace(device_prefetch=dev,
                             checkpoint_dir=str(cdir),
                             logs_path=str(ldir)))
        results[name] = r
        params[name] = np.load(ckpt.latest_checkpoint(str(cdir)),
                               allow_pickle=False)
    rb, rp = results["blocking"], results["prefetched"]
    assert rb["final_cost"] == rp["final_cost"]
    assert rb["test_accuracy"] == rp["test_accuracy"]
    assert rb["steps"] == rp["steps"]
    a, b = params["blocking"], params["prefetched"]
    assert a.files == b.files and len(a.files) > 0
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


@needs_stack
def test_device_prefetch_populates_h2d_bucket(tmp_path):
    """--device_prefetch + --metrics: the h2d goodput bucket is
    populated and the decomposition still sums to within 5% of wall."""
    from distributed_tensorflow_example_tpu.config import Config
    from distributed_tensorflow_example_tpu.obs.aggregate import aggregate
    from distributed_tensorflow_example_tpu.train.loop import run

    ldir = str(tmp_path / "logs")
    run(Config(batch_size=32, dataset="synthetic",
               synthetic_train_size=32 * 8, synthetic_test_size=64,
               training_epochs=2, summaries=False, fast_loop=False,
               frequency=1000, metrics=True, log_every=4,
               device_prefetch=True, logs_path=ldir))
    rep = aggregate(ldir)
    g = rep["goodput"]
    assert g["buckets"]["h2d"] > 0.0
    assert abs(g["bucket_sum_s"] - g["wall_s"]) <= 0.05 * g["wall_s"]
    assert rep["schema_errors"] == []


@needs_stack
def test_depth_flags_validated():
    from distributed_tensorflow_example_tpu.config import Config
    from distributed_tensorflow_example_tpu.train.loop import run

    with pytest.raises(ValueError, match="dispatch_depth"):
        run(Config(dispatch_depth=-1))
    with pytest.raises(ValueError, match="prefetch_depth"):
        run(Config(prefetch_depth=-2))
