"""Prefetcher: same batches, same order, errors propagate."""

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.data import EpochIterator, Prefetcher
from distributed_tensorflow_example_tpu.data import mnist as M


def test_prefetcher_preserves_batches():
    split = M.synthesize_split(100, seed=3)
    a = list(EpochIterator(split, batch_size=10, seed=1, shard=False).epoch())
    b = list(Prefetcher(EpochIterator(split, batch_size=10, seed=1, shard=False).epoch()))
    assert len(a) == len(b) == 10
    for (ax, ay), (bx, by) in zip(a, b):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetcher_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")

    p = Prefetcher(gen())
    it = iter(p)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetcher_close_unblocks_producer():
    import itertools, time

    produced = []

    def gen():
        for i in itertools.count():
            produced.append(i)
            yield i

    p = Prefetcher(gen(), depth=2)
    it = iter(p)
    assert next(it) == 0
    p.close()
    p._thread.join(timeout=5)
    assert not p._thread.is_alive()
    # producer stopped promptly: queue depth 2 + in-flight item bound
    assert len(produced) < 10
