"""Learning-regime accuracy evidence (VERDICT r2 missing #1).

The reference's published use is training MNIST to a real accuracy
(/root/reference/example.py:47-48 read_data_sets; example.py:177
Test-Accuracy print). The reference CONSTANTS (N(0,1) init, sigmoid,
lr 5e-4) barely train — the oracle tests pin that regime's dynamics —
so these tests raise ONLY the learning-rate flag (5e-4 -> 0.5) and
assert the same architecture + naive CE actually learns to a
meaningful accuracy, both from the synthetic set and end-to-end from
real IDX files through the --dataset=mnist pipeline.
"""

import struct

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.data import mnist as M
from distributed_tensorflow_example_tpu.train.loop import run


def test_learning_regime_reference_arch(capsys):
    """sigmoid 784-100-10 + SGD + naive log(softmax) CE at lr=0.5:
    must reach >= 0.85 test accuracy (chance is 0.10) in 5 epochs."""
    res = run(Config(
        learning_rate=0.5, naive_ce=True, training_epochs=5,
        summaries=False, compilation_cache="",
        synthetic_train_size=8192, synthetic_test_size=2048,
    ))
    assert res["test_accuracy"] >= 0.85, res
    assert np.isfinite(res["final_cost"])


def _write_idx(data_dir, images_f32, labels_onehot, prefix):
    """Serialize a (images [N,784] in [0,1], one-hot labels) split as
    the two canonical IDX files."""
    n = images_f32.shape[0]
    pix = np.round(images_f32 * 255.0).astype(np.uint8).reshape(n, 28, 28)
    lab = np.argmax(labels_onehot, axis=1).astype(np.uint8)
    img_name = M.TRAIN_IMAGES if prefix == "train" else M.TEST_IMAGES
    lab_name = M.TRAIN_LABELS if prefix == "train" else M.TEST_LABELS
    (data_dir / img_name).write_bytes(
        struct.pack(">IIII", M.IMAGE_MAGIC, n, 28, 28) + pix.tobytes())
    (data_dir / lab_name).write_bytes(
        struct.pack(">II", M.LABEL_MAGIC, n) + lab.tobytes())


def test_idx_end_to_end_learning(tmp_path, monkeypatch):
    """Full --dataset=mnist path on real IDX files: parse from disk,
    train the reference architecture in the learning regime, reach a
    meaningful accuracy. (The files carry the learnable glyph data —
    real MNIST bytes are unavailable offline — but every byte flows
    through the same IDX parse + train + eval pipeline read_data_sets
    fed, example.py:47-48.)"""
    monkeypatch.setattr(M, "VALIDATION_SIZE", 100)
    train = M.synthesize_split(3100, seed=11)
    test = M.synthesize_split(400, seed=12)
    _write_idx(tmp_path, train.images, train.labels, "train")
    _write_idx(tmp_path, test.images, test.labels, "test")

    res = run(Config(
        dataset="mnist", data_dir=str(tmp_path),
        learning_rate=0.5, naive_ce=True, training_epochs=20,
        summaries=False, compilation_cache="",
    ))
    assert res["dataset_source"] == "mnist"
    assert res["test_accuracy"] >= 0.85, res
