"""Pallas fused-forward tests (interpret mode on the CPU backend):
numerical parity with the XLA forward, custom-VJP gradients, and
DP-sharded training equivalence through shard_map."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models import mlp
from distributed_tensorflow_example_tpu.ops import pallas_fused

SPECS = [
    mlp.MLPSpec(input_size=16, hidden_sizes=(8,), num_classes=4),
    mlp.MLPSpec(input_size=16, hidden_sizes=(12, 8), num_classes=4,
                activation="relu"),
]


@pytest.mark.parametrize("spec", SPECS, ids=["sigmoid1", "relu2"])
def test_forward_matches_xla(spec):
    params = mlp.init(jax.random.PRNGKey(0), spec)
    x = np.random.RandomState(0).rand(20, spec.input_size).astype(np.float32)
    want = np.asarray(mlp.apply(spec, params, x))
    got = np.asarray(pallas_fused.mlp_forward(spec, params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("spec", SPECS, ids=["sigmoid1", "relu2"])
def test_grads_match_xla(spec):
    params = mlp.init(jax.random.PRNGKey(0), spec)
    rng = np.random.RandomState(1)
    x = rng.rand(20, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, 20)
    ]

    def loss(p, fwd):
        logits = fwd(spec, p, x)
        return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), axis=-1))

    g_xla = jax.grad(lambda p: loss(p, lambda s, p_, x_: mlp.apply(s, p_, x_)))(params)
    g_pal = jax.grad(lambda p: loss(p, pallas_fused.mlp_forward))(params)
    for k in g_xla:
        np.testing.assert_allclose(
            np.asarray(g_pal[k]), np.asarray(g_xla[k]), rtol=1e-4, atol=1e-5,
            err_msg=k,
        )


def test_forward_matches_xla_bfloat16():
    """--pallas with --compute_dtype=bfloat16 must compute the same
    layer-for-layer math as the XLA forward (bf16 matmul inputs, f32
    accumulate/bias/activate, round at layer edges) — ADVICE r1."""
    spec = mlp.MLPSpec(
        input_size=16, hidden_sizes=(8,), num_classes=4,
        compute_dtype=jnp.bfloat16,
    )
    params = mlp.init(jax.random.PRNGKey(0), spec)
    x = np.random.RandomState(0).rand(20, spec.input_size).astype(np.float32)
    want = np.asarray(mlp.apply(spec, params, x))
    got = np.asarray(pallas_fused.mlp_forward(spec, params, x))
    assert got.dtype == np.float32
    # identical op sequence; tolerance only covers backend reduction-order
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
    # and bf16 really is lower precision than f32 — sanity that the cast
    # path was exercised (bf16 forward differs from the f32 forward)
    f32_spec = mlp.MLPSpec(input_size=16, hidden_sizes=(8,), num_classes=4)
    f32_out = np.asarray(mlp.apply(f32_spec, params, x))
    assert not np.array_equal(want, f32_out)


def test_grads_match_xla_bfloat16():
    """The custom-VJP backward's compute_dtype casts (bf16 matmul
    inputs, f32 accumulation and delta chain) must track the XLA
    autodiff gradients of the same bf16 forward to bf16-scale
    tolerance — the f32 tests elide every one of those casts."""
    spec = mlp.MLPSpec(
        input_size=16, hidden_sizes=(12, 8), num_classes=4,
        activation="relu", compute_dtype=jnp.bfloat16,
    )
    params = mlp.init(jax.random.PRNGKey(0), spec)
    rng = np.random.RandomState(1)
    x = rng.rand(24, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, 24)
    ]

    def loss(p, fwd):
        logits = fwd(spec, p, x)
        return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), axis=-1))

    g_xla = jax.grad(lambda p: loss(p, lambda s, p_, x_: mlp.apply(s, p_, x_)))(params)
    g_pal = jax.grad(lambda p: loss(p, pallas_fused.mlp_forward))(params)
    for k in g_xla:
        ref = np.asarray(g_xla[k])
        scale = max(np.abs(ref).max(), 1e-3)
        np.testing.assert_allclose(
            np.asarray(g_pal[k]) / scale, ref / scale, atol=2e-2, err_msg=k,
        )


def test_dp8_training_equivalence_with_pallas(devices8):
    """One DP-8 sharded pallas step == the XLA step (the custom-VJP
    psum reinsertion is load-bearing here)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = SPECS[0]
    rng = np.random.RandomState(0)
    x = rng.rand(96, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, 96)
    ]

    def one_step(use_pallas):
        cfg = Config(learning_rate=0.05, pallas=use_pallas)
        mesh = mesh_lib.build_mesh(8, 1)
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1)
        )
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        state, cost, _ = step(state, x, y)
        return jax.device_get(state.params), float(cost)

    p_ref, c_ref = one_step(False)
    p_pal, c_pal = one_step(True)
    assert abs(c_ref - c_pal) < 1e-5
    for k in p_ref:
        np.testing.assert_allclose(p_pal[k], p_ref[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
