"""Pallas fused-kernel tests (interpret mode on the CPU backend):
numerical parity with the XLA paths they replace, custom-VJP
gradients, and sharded training equivalence through shard_map — for
the fused MLP forward, the fused LayerNorm(+residual) fwd+bwd
kernels, and the grouped MoE expert matmul."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import Config
from distributed_tensorflow_example_tpu.models import mlp
from distributed_tensorflow_example_tpu.models import transformer as tfm
from distributed_tensorflow_example_tpu.ops import pallas_fused

from conftest import needs_stack  # noqa: E402

SPECS = [
    mlp.MLPSpec(input_size=16, hidden_sizes=(8,), num_classes=4),
    mlp.MLPSpec(input_size=16, hidden_sizes=(12, 8), num_classes=4,
                activation="relu"),
]


@pytest.mark.parametrize("spec", SPECS, ids=["sigmoid1", "relu2"])
def test_forward_matches_xla(spec):
    params = mlp.init(jax.random.PRNGKey(0), spec)
    x = np.random.RandomState(0).rand(20, spec.input_size).astype(np.float32)
    want = np.asarray(mlp.apply(spec, params, x))
    got = np.asarray(pallas_fused.mlp_forward(spec, params, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("spec", SPECS, ids=["sigmoid1", "relu2"])
def test_grads_match_xla(spec):
    params = mlp.init(jax.random.PRNGKey(0), spec)
    rng = np.random.RandomState(1)
    x = rng.rand(20, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, 20)
    ]

    def loss(p, fwd):
        logits = fwd(spec, p, x)
        return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), axis=-1))

    g_xla = jax.grad(lambda p: loss(p, lambda s, p_, x_: mlp.apply(s, p_, x_)))(params)
    g_pal = jax.grad(lambda p: loss(p, pallas_fused.mlp_forward))(params)
    for k in g_xla:
        np.testing.assert_allclose(
            np.asarray(g_pal[k]), np.asarray(g_xla[k]), rtol=1e-4, atol=1e-5,
            err_msg=k,
        )


def test_forward_matches_xla_bfloat16():
    """--pallas with --compute_dtype=bfloat16 must compute the same
    layer-for-layer math as the XLA forward (bf16 matmul inputs, f32
    accumulate/bias/activate, round at layer edges) — ADVICE r1."""
    spec = mlp.MLPSpec(
        input_size=16, hidden_sizes=(8,), num_classes=4,
        compute_dtype=jnp.bfloat16,
    )
    params = mlp.init(jax.random.PRNGKey(0), spec)
    x = np.random.RandomState(0).rand(20, spec.input_size).astype(np.float32)
    want = np.asarray(mlp.apply(spec, params, x))
    got = np.asarray(pallas_fused.mlp_forward(spec, params, x))
    assert got.dtype == np.float32
    # identical op sequence; tolerance only covers backend reduction-order
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
    # and bf16 really is lower precision than f32 — sanity that the cast
    # path was exercised (bf16 forward differs from the f32 forward)
    f32_spec = mlp.MLPSpec(input_size=16, hidden_sizes=(8,), num_classes=4)
    f32_out = np.asarray(mlp.apply(f32_spec, params, x))
    assert not np.array_equal(want, f32_out)


def test_grads_match_xla_bfloat16():
    """The custom-VJP backward's compute_dtype casts (bf16 matmul
    inputs, f32 accumulation and delta chain) must track the XLA
    autodiff gradients of the same bf16 forward to bf16-scale
    tolerance — the f32 tests elide every one of those casts."""
    spec = mlp.MLPSpec(
        input_size=16, hidden_sizes=(12, 8), num_classes=4,
        activation="relu", compute_dtype=jnp.bfloat16,
    )
    params = mlp.init(jax.random.PRNGKey(0), spec)
    rng = np.random.RandomState(1)
    x = rng.rand(24, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, 24)
    ]

    def loss(p, fwd):
        logits = fwd(spec, p, x)
        return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), axis=-1))

    g_xla = jax.grad(lambda p: loss(p, lambda s, p_, x_: mlp.apply(s, p_, x_)))(params)
    g_pal = jax.grad(lambda p: loss(p, pallas_fused.mlp_forward))(params)
    for k in g_xla:
        ref = np.asarray(g_xla[k])
        scale = max(np.abs(ref).max(), 1e-3)
        np.testing.assert_allclose(
            np.asarray(g_pal[k]) / scale, ref / scale, atol=2e-2, err_msg=k,
        )


# ---------------------------------------------------------------------------
# Fused LayerNorm (+residual) — oracle parity vs transformer._layer_norm
# (ISSUE 6 tentpole (a)); interpret mode on CPU, so these are tier-1.
# ---------------------------------------------------------------------------

# rank-2 (the decode/sampling shape) and rank-3 (the training shape),
# even and ODD feature widths (odd d exercises the lane-padding path
# on TPU and the non-tile-aligned interpreter path here)
_LN_SHAPES = [((6, 64), "rank2_even"), ((5, 33), "rank2_odd"),
              ((2, 7, 64), "rank3_even"), ((3, 5, 33), "rank3_odd"),
              ((4, 129, 96), "rank3_multi_tile")]


@pytest.mark.parametrize("shape",
                         [s for s, _ in _LN_SHAPES],
                         ids=[i for _, i in _LN_SHAPES])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_fused_ln_matches_oracle(shape, dtype):
    """Forward parity: identical op sequence to _layer_norm (f32
    statistics, f32 output) over every rank/width/dtype crossing."""
    rng = np.random.RandomState(0)
    d = shape[-1]
    x = jnp.asarray(rng.randn(*shape), dtype)
    g = jnp.asarray(rng.randn(d), jnp.float32)
    b = jnp.asarray(rng.randn(d), jnp.float32)
    want = np.asarray(tfm._layer_norm(x, g, b))
    got = np.asarray(pallas_fused.fused_layer_norm(x, g, b))
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape", [(5, 33), (3, 5, 33), (2, 7, 64)],
                         ids=["rank2_odd", "rank3_odd", "rank3_even"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_fused_ln_grads_match_oracle(shape, dtype):
    """Backward parity: the Pallas backward kernel's dx/dg/db against
    jax.grad through the XLA reference, for both input dtypes (bf16
    dx rounds exactly where the reference autodiff rounds)."""
    rng = np.random.RandomState(1)
    d = shape[-1]
    x = jnp.asarray(rng.randn(*shape), dtype)
    g = jnp.asarray(rng.randn(d), jnp.float32)
    b = jnp.asarray(rng.randn(d), jnp.float32)
    w = jnp.asarray(rng.randn(*shape), jnp.float32)

    def loss(fn):
        return lambda x_, g_, b_: jnp.sum(fn(x_, g_, b_) * w)

    ref = jax.grad(loss(tfm._layer_norm), (0, 1, 2))(x, g, b)
    got = jax.grad(loss(pallas_fused.fused_layer_norm), (0, 1, 2))(x, g, b)
    tol = dict(rtol=1e-4, atol=1e-5) if dtype == jnp.float32 \
        else dict(rtol=2e-2, atol=2e-2)
    for r, gt, name in zip(ref, got, ("dx", "dg", "db")):
        assert np.asarray(gt).dtype == np.asarray(r).dtype, name
        np.testing.assert_allclose(np.asarray(gt), np.asarray(r),
                                   err_msg=name, **tol)


def test_fused_ln_residual_matches_oracle():
    """The residual-fused variant: (LN(x+r), x+r) with BOTH outputs'
    cotangents flowing — dy through the LN backward kernel, ds
    directly — must match the unfused x + r; LN(s) composition in
    values and all four gradients."""
    rng = np.random.RandomState(2)
    shape, d = (3, 6, 48), 48
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    r = jnp.asarray(rng.randn(*shape), jnp.float32)
    g = jnp.asarray(rng.randn(d), jnp.float32)
    b = jnp.asarray(rng.randn(d), jnp.float32)
    w1 = jnp.asarray(rng.randn(*shape), jnp.float32)
    w2 = jnp.asarray(rng.randn(*shape), jnp.float32)

    y, s = pallas_fused.fused_layer_norm_residual(x, r, g, b)
    np.testing.assert_allclose(np.asarray(s), np.asarray(x + r),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(tfm._layer_norm(x + r, g, b)),
        rtol=1e-5, atol=1e-5)

    def loss_ref(x_, r_, g_, b_):
        s_ = x_ + r_
        return (jnp.sum(tfm._layer_norm(s_, g_, b_) * w1)
                + jnp.sum(s_ * w2))

    def loss_fused(x_, r_, g_, b_):
        y_, s_ = pallas_fused.fused_layer_norm_residual(x_, r_, g_, b_)
        return jnp.sum(y_ * w1) + jnp.sum(s_ * w2)

    ref = jax.grad(loss_ref, (0, 1, 2, 3))(x, r, g, b)
    got = jax.grad(loss_fused, (0, 1, 2, 3))(x, r, g, b)
    for a, c, name in zip(ref, got, ("dx", "dr", "dg", "db")):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_fused_ln_residual_bf16_normalizes_rounded_sum():
    """bf16 inputs: the kernel must normalize the ROUNDED sum it emits
    (s = bf16(x + r)), exactly like the unfused `s = x + r; LN(s)`
    composition — statistics on the unrounded f32 sum would disagree
    with the returned s and with the VJP's recompute-from-s."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(4, 48), jnp.bfloat16)
    r = jnp.asarray(rng.randn(4, 48), jnp.bfloat16)
    g = jnp.asarray(rng.randn(48), jnp.float32)
    b = jnp.asarray(rng.randn(48), jnp.float32)
    y, s = pallas_fused.fused_layer_norm_residual(x, r, g, b)
    assert np.asarray(s).dtype == jnp.bfloat16
    s_ref = x + r   # bf16 rounded, the composition's actual stream
    np.testing.assert_array_equal(np.asarray(s, np.float32),
                                  np.asarray(s_ref, np.float32))
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(tfm._layer_norm(s_ref, g, b)),
        rtol=1e-5, atol=1e-5)


def test_fused_ln_rank2_decode_site():
    """The decode path's exact call pattern (rank-2 [B, d] direct —
    the old ``[:, None]...[:, 0]`` dance is gone): fused and reference
    agree, and BOTH accept rank-2 without reshaping."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 32), jnp.float32)
    g = jnp.asarray(rng.randn(32), jnp.float32)
    b = jnp.asarray(rng.randn(32), jnp.float32)
    direct = np.asarray(tfm._layer_norm(x, g, b))
    danced = np.asarray(tfm._layer_norm(x[:, None], g, b)[:, 0])
    np.testing.assert_allclose(direct, danced, rtol=0, atol=0)
    got = np.asarray(pallas_fused.fused_layer_norm(x, g, b))
    np.testing.assert_allclose(got, direct, rtol=1e-5, atol=1e-5)


def test_fused_ln_param_dtype_bf16():
    """bf16 gains/biases (param_dtype=bfloat16 runs): cotangents come
    back in the params' dtype with the reference's values."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(6, 64), jnp.float32)
    g = jnp.asarray(rng.randn(64), jnp.bfloat16)
    b = jnp.asarray(rng.randn(64), jnp.bfloat16)
    want = np.asarray(tfm._layer_norm(x, g, b))
    got = np.asarray(pallas_fused.fused_layer_norm(x, g, b))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    w = jnp.asarray(rng.randn(6, 64), jnp.float32)
    gref = jax.grad(lambda g_: jnp.sum(tfm._layer_norm(x, g_, b) * w))(g)
    gpal = jax.grad(
        lambda g_: jnp.sum(pallas_fused.fused_layer_norm(x, g_, b) * w))(g)
    assert gpal.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(gpal, np.float32),
                               np.asarray(gref, np.float32),
                               rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# Grouped MoE expert matmul — oracle parity vs the XLA grouped einsums
# (ISSUE 6 tentpole (b))
# ---------------------------------------------------------------------------


def _moe_ref(act, cdt, buf, we1, be1, we2, be2):
    """The XLA grouped-einsum path the kernel replaces (the
    spec.grouped_moe=False branch of transformer._grouped_expert_ffn),
    inlined as the oracle."""
    h1 = act(jnp.einsum("ecd,edf->ecf", buf.astype(cdt), we1.astype(cdt),
                        preferred_element_type=jnp.float32)
             + be1[:, None].astype(jnp.float32)).astype(cdt)
    return jnp.einsum("ecf,efd->ecd", h1, we2.astype(cdt),
                      preferred_element_type=jnp.float32) \
        + be2[:, None].astype(jnp.float32)


@pytest.mark.parametrize("activation", ["gelu", "relu"])
@pytest.mark.parametrize("cdt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_moe_grouped_matmul_matches_xla(activation, cdt):
    """Forward parity on a ragged capacity (C=37, off the 128 tile):
    identical mixed precision to the einsum path — cdt matmul inputs,
    f32 accumulate/bias, hidden rounded to cdt between the matmuls.
    gelu matters: its VJP needs the PRE-activation residual the kernel
    saves (the MLP kernel's output-derivative trick can't cover it)."""
    rng = np.random.RandomState(0)
    e, c, d, ff = 4, 37, 16, 24
    buf = jnp.asarray(rng.randn(e, c, d), jnp.float32)
    we1 = jnp.asarray(rng.randn(e, d, ff) / np.sqrt(d), jnp.float32)
    be1 = jnp.asarray(rng.randn(e, ff), jnp.float32)
    we2 = jnp.asarray(rng.randn(e, ff, d) / np.sqrt(ff), jnp.float32)
    be2 = jnp.asarray(rng.randn(e, d), jnp.float32)
    act = mlp._ACTIVATIONS[activation]
    want = np.asarray(_moe_ref(act, cdt, buf, we1, be1, we2, be2))
    got = np.asarray(pallas_fused.moe_grouped_matmul(
        activation, cdt, buf, we1, be1, we2, be2))
    assert got.dtype == np.float32
    tol = dict(rtol=1e-5, atol=1e-5) if cdt == jnp.float32 \
        else dict(rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(got, want, **tol)


@pytest.mark.parametrize("activation", ["gelu", "relu"])
def test_moe_grouped_matmul_grads_match_xla(activation):
    """Gradient parity for all five inputs against jax.grad through
    the einsum oracle (the custom VJP recomputes the activation from
    the saved pre-activation via jax.vjp — exact for gelu too)."""
    rng = np.random.RandomState(1)
    e, c, d, ff = 3, 20, 8, 12
    args = (jnp.asarray(rng.randn(e, c, d), jnp.float32),
            jnp.asarray(rng.randn(e, d, ff) / np.sqrt(d), jnp.float32),
            jnp.asarray(rng.randn(e, ff), jnp.float32),
            jnp.asarray(rng.randn(e, ff, d) / np.sqrt(ff), jnp.float32),
            jnp.asarray(rng.randn(e, d), jnp.float32))
    w = jnp.asarray(rng.randn(e, c, d), jnp.float32)
    act = mlp._ACTIVATIONS[activation]
    ref = jax.grad(lambda *a: jnp.sum(
        _moe_ref(act, jnp.float32, *a) * w), tuple(range(5)))(*args)
    got = jax.grad(lambda *a: jnp.sum(pallas_fused.moe_grouped_matmul(
        activation, jnp.float32, *a) * w), tuple(range(5)))(*args)
    names = ("dbuf", "dwe1", "dbe1", "dwe2", "dbe2")
    for r, gt, name in zip(ref, got, names):
        np.testing.assert_allclose(np.asarray(gt), np.asarray(r),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_grouped_expert_ffn_dispatches_to_kernel():
    """transformer._grouped_expert_ffn: the spec switch really selects
    the kernel (grouped_moe=True) vs the einsums, and both agree."""
    rng = np.random.RandomState(2)
    e, c, d, ff = 4, 16, 8, 12
    spec = tfm.TransformerSpec(input_size=784, seq_len=28, d_model=d,
                               n_heads=2, num_blocks=1, d_ff=ff,
                               num_experts=e)
    buf = jnp.asarray(rng.randn(e, c, d), jnp.float32)
    we1 = jnp.asarray(rng.randn(e, d, ff), jnp.float32)
    be1 = jnp.asarray(rng.randn(e, ff), jnp.float32)
    we2 = jnp.asarray(rng.randn(e, ff, d), jnp.float32)
    be2 = jnp.asarray(rng.randn(e, d), jnp.float32)
    act = mlp._ACTIVATIONS[spec.activation]
    xla = np.asarray(tfm._grouped_expert_ffn(
        spec, buf, we1, be1, we2, be2, act, jnp.float32))
    import dataclasses

    kern = np.asarray(tfm._grouped_expert_ffn(
        dataclasses.replace(spec, grouped_moe=True),
        buf, we1, be1, we2, be2, act, jnp.float32))
    np.testing.assert_allclose(kern, xla, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# fp8 FFN matmuls (ISSUE 11 tentpole (b)): the custom_vjp oracle
# matrix — fwd + all five grads vs the f32 reference within the
# DOCUMENTED bounds (docs/quantization.md), plus the exact-emulation
# identity (fp8 kernel == bf16 kernel on pre-rounded operands) and
# the spec dispatch switches.
# ---------------------------------------------------------------------------


def _fp8_args(seed=0, e=3, c=37, d=16, ff=24):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(e, c, d), jnp.float32),
            jnp.asarray(rng.randn(e, d, ff) / np.sqrt(d), jnp.float32),
            jnp.asarray(rng.randn(e, ff) * 0.1, jnp.float32),
            jnp.asarray(rng.randn(e, ff, d) / np.sqrt(ff), jnp.float32),
            jnp.asarray(rng.randn(e, d) * 0.1, jnp.float32))


@pytest.mark.parametrize("activation", ["gelu", "relu"])
@pytest.mark.parametrize("cdt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_fp8_grouped_matmul_fwd_within_bounds(activation, cdt):
    """Forward vs the f32 einsum reference: max abs error <= 10% of
    the reference's max magnitude (e4m3's 3-bit mantissa rounds each
    operand within 2^-4 relative; two matmuls + the activation
    compound to the documented <= 0.10 bound — measured ~0.05 on
    these shapes)."""
    args = _fp8_args()
    act = mlp._ACTIVATIONS[activation]
    want = np.asarray(_moe_ref(act, jnp.float32, *args))
    got = np.asarray(pallas_fused.fp8_grouped_matmul(activation, cdt,
                                                     *args))
    assert got.dtype == np.float32
    rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
    assert rel <= 0.10, rel
    # and the rounding genuinely happened: fp8 is NOT bit-equal to
    # the unquantized path (a silent no-op would also pass the bound)
    assert np.max(np.abs(got - want)) > 0.0


@pytest.mark.parametrize("activation", ["gelu", "relu"])
def test_fp8_grouped_matmul_grads_within_bounds(activation):
    """All five cotangents vs jax.grad through the f32 reference:
    straight-through estimator + backward on the saved QUANTIZED
    residuals.  Documented bounds: <= 0.15 relative for the smooth
    activation, <= 0.35 for relu (operand rounding flips step-function
    mask bits near zero — individual elements jump while the bulk
    stays tight)."""
    args = _fp8_args(1, e=3, c=20, d=8, ff=12)
    w = jnp.asarray(np.random.RandomState(9).randn(3, 20, 8),
                    jnp.float32)
    act = mlp._ACTIVATIONS[activation]
    ref = jax.grad(lambda *a: jnp.sum(
        _moe_ref(act, jnp.float32, *a) * w), tuple(range(5)))(*args)
    got = jax.grad(lambda *a: jnp.sum(pallas_fused.fp8_grouped_matmul(
        activation, jnp.float32, *a) * w), tuple(range(5)))(*args)
    bound = 0.15 if activation == "gelu" else 0.35
    names = ("dbuf", "dwe1", "dbe1", "dwe2", "dbe2")
    for r, gt, name in zip(ref, got, names):
        rel = float(np.max(np.abs(np.asarray(gt) - np.asarray(r)))
                    / (np.max(np.abs(np.asarray(r))) + 1e-9))
        assert rel <= bound, (name, rel)


@pytest.mark.parametrize("activation", ["gelu", "relu"])
def test_fp8_equals_kernel_on_prerounded_operands(activation):
    """THE emulation identity: fp8_grouped_matmul(x, w1, w2) ==
    moe_grouped_matmul(fp8_round(x), fp8_round(w1), fp8_round(w2))
    bitwise — the fp8 path IS the fused kernel on fp8-grid operands,
    so there is no second kernel body to drift."""
    from distributed_tensorflow_example_tpu.ops import quant

    buf, we1, be1, we2, be2 = _fp8_args(2)
    got = np.asarray(pallas_fused.fp8_grouped_matmul(
        activation, jnp.float32, buf, we1, be1, we2, be2))
    want = np.asarray(pallas_fused.moe_grouped_matmul(
        activation, jnp.float32,
        quant.fp8_round(buf, axis=(1, 2)),
        quant.fp8_round(we1, axis=(1, 2)), be1,
        quant.fp8_round(we2, axis=(1, 2)), be2))
    np.testing.assert_array_equal(got, want)


def test_fp8_dense_ffn_matches_dense_reference():
    """The dense wrapper (E=1 grouped call) vs the plain two-matmul
    FFN on the same operands, within the fwd bound; shape [T, d] in
    and out."""
    rng = np.random.RandomState(3)
    t, d, ff = 50, 16, 32
    x2 = jnp.asarray(rng.randn(t, d), jnp.float32)
    w1 = jnp.asarray(rng.randn(d, ff) / np.sqrt(d), jnp.float32)
    b1 = jnp.asarray(rng.randn(ff) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.randn(ff, d) / np.sqrt(ff), jnp.float32)
    b2 = jnp.asarray(rng.randn(d) * 0.1, jnp.float32)
    want = np.asarray(
        jnp.dot(jax.nn.gelu(jnp.dot(x2, w1) + b1), w2) + b2)
    got = np.asarray(pallas_fused.fp8_dense_ffn(
        "gelu", jnp.float32, x2, w1, b1, w2, b2))
    assert got.shape == (t, d)
    rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
    assert 0.0 < rel <= 0.10, rel


def test_fp8_ffn_spec_dispatch():
    """TransformerSpec.fp8_ffn really switches both FFN families: the
    grouped expert path routes to fp8_grouped_matmul, and the dense
    _ffn_block branch routes through fp8_dense_ffn — each equal to
    calling the kernel directly."""
    import dataclasses

    rng = np.random.RandomState(4)
    e, c, d, ff = 4, 16, 8, 12
    spec = tfm.TransformerSpec(input_size=784, seq_len=28, d_model=d,
                               n_heads=2, num_blocks=1, d_ff=ff,
                               num_experts=e)
    args = (jnp.asarray(rng.randn(e, c, d), jnp.float32),
            jnp.asarray(rng.randn(e, d, ff), jnp.float32),
            jnp.asarray(rng.randn(e, ff), jnp.float32),
            jnp.asarray(rng.randn(e, ff, d), jnp.float32),
            jnp.asarray(rng.randn(e, d), jnp.float32))
    act = mlp._ACTIVATIONS[spec.activation]
    via_spec = np.asarray(tfm._grouped_expert_ffn(
        dataclasses.replace(spec, fp8_ffn=True), *args, act,
        jnp.float32))
    direct = np.asarray(pallas_fused.fp8_grouped_matmul(
        spec.activation, jnp.float32, *args))
    np.testing.assert_array_equal(via_spec, direct)
    # ... and differs from the unquantized path (the switch is live)
    plain = np.asarray(tfm._grouped_expert_ffn(spec, *args, act,
                                               jnp.float32))
    assert np.max(np.abs(via_spec - plain)) > 0.0

    # dense branch: _ffn_block with fp8_ffn == residual + fp8_dense_ffn
    dspec = tfm.TransformerSpec(input_size=784, seq_len=28, d_model=d,
                                n_heads=2, num_blocks=1, d_ff=ff,
                                fp8_ffn=True)
    bp = {"ln2_g": jnp.ones(d), "ln2_b": jnp.zeros(d),
          "W1": args[1][0], "b1": args[2][0],
          "W2": args[3][0], "b2": args[4][0]}
    h = jnp.asarray(rng.randn(2, 5, d), jnp.float32)
    out, _aux = tfm._ffn_block(dspec, bp, h, act, jnp.float32)
    a = tfm._layer_norm(h, bp["ln2_g"], bp["ln2_b"])
    want = h + pallas_fused.fp8_dense_ffn(
        dspec.activation, jnp.float32, a.reshape(10, d),
        bp["W1"], bp["b1"], bp["W2"], bp["b2"]).reshape(2, 5, d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # tensor parallelism is rejected at the dispatch (the pure-config
    # validator pins the flag matrix; this guards direct callers)
    with pytest.raises(ValueError, match="tensor"):
        tfm._ffn_block(dspec, bp, h, act, jnp.float32,
                       model_axis="model")


# ---------------------------------------------------------------------------
# End-to-end: --fused_ln training equivalence (stack-gated: needs the
# full mesh/shard_map step; the kernel itself is covered tier-1 above)
# ---------------------------------------------------------------------------


@needs_stack
def test_fused_ln_training_equivalence(devices8):
    """--fused_ln training reaches the same final params as the
    reference path on the tiny transformer config: 4 steps of the real
    build_train_step on a DP-2 mesh, params compared
    bit-identical-within-tolerance (the fused forward is the same f32
    math; only reduction order may differ)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.loop import make_spec
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    rng = np.random.RandomState(0)
    x = rng.rand(4 * 16, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4 * 16)]

    def train(fused):
        cfg = Config(model="transformer", d_model=32, n_heads=2,
                     num_blocks=2, d_ff=64, learning_rate=0.05,
                     fused_ln=fused)
        spec = make_spec(cfg)
        mesh = mesh_lib.build_mesh(2, 1, devices=devices8[:2])
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        for i in range(4):
            state, cost, _ = step(state, x[i * 16:(i + 1) * 16],
                                  y[i * 16:(i + 1) * 16])
        return jax.tree.map(np.asarray, state.params), float(cost)

    p_ref, c_ref = train(False)
    p_fus, c_fus = train(True)
    assert abs(c_ref - c_fus) < 1e-5
    for k in p_ref:
        np.testing.assert_allclose(p_fus[k], p_ref[k], rtol=1e-4,
                                   atol=1e-6, err_msg=k)


@needs_stack
def test_grouped_moe_training_step_equivalence(devices8):
    """One sparse-MoE training step with --grouped_moe == the XLA
    einsum step (ample capacity so the two paths see identical
    buffers), through the real sharded step."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.loop import make_spec
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    rng = np.random.RandomState(0)
    x = rng.rand(16, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]

    def one_step(grouped):
        cfg = Config(model="transformer", d_model=32, n_heads=2,
                     num_blocks=2, d_ff=64, num_experts=4,
                     moe_dispatch="alltoall", capacity_factor=4.0,
                     learning_rate=0.05, grouped_moe=grouped)
        spec = make_spec(cfg)
        mesh = mesh_lib.build_mesh(2, 1, devices=devices8[:2])
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1))
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        state, cost, _ = step(state, x, y)
        return jax.tree.map(np.asarray, state.params), float(cost)

    p_ref, c_ref = one_step(False)
    p_grp, c_grp = one_step(True)
    assert abs(c_ref - c_grp) < 1e-5
    for k in p_ref:
        np.testing.assert_allclose(p_grp[k], p_ref[k], rtol=1e-4,
                                   atol=1e-6, err_msg=k)


def test_dp8_training_equivalence_with_pallas(devices8):
    """One DP-8 sharded pallas step == the XLA step (the custom-VJP
    psum reinsertion is load-bearing here)."""
    from distributed_tensorflow_example_tpu.parallel import mesh as mesh_lib
    from distributed_tensorflow_example_tpu.parallel import step as step_lib
    from distributed_tensorflow_example_tpu.train.optim import make_optimizer
    from distributed_tensorflow_example_tpu.train.state import create_train_state

    spec = SPECS[0]
    rng = np.random.RandomState(0)
    x = rng.rand(96, spec.input_size).astype(np.float32)
    y = np.eye(spec.num_classes, dtype=np.float32)[
        rng.randint(0, spec.num_classes, 96)
    ]

    def one_step(use_pallas):
        cfg = Config(learning_rate=0.05, pallas=use_pallas)
        mesh = mesh_lib.build_mesh(8, 1)
        opt = make_optimizer(cfg)
        state = create_train_state(jax.random.PRNGKey(1), spec, opt)
        state = mesh_lib.place_state(
            state, mesh, mesh_lib.state_pspecs(spec, opt, 1)
        )
        step = step_lib.build_train_step(cfg, mesh, spec, opt)
        state, cost, _ = step(state, x, y)
        return jax.device_get(state.params), float(cost)

    p_ref, c_ref = one_step(False)
    p_pal, c_pal = one_step(True)
    assert abs(c_ref - c_pal) < 1e-5
    for k in p_ref:
        np.testing.assert_allclose(p_pal[k], p_ref[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
