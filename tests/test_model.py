"""Model tests: init shapes/dtypes, forward vs numpy oracle (SURVEY.md §4)."""

import jax
import numpy as np

from distributed_tensorflow_example_tpu.models import mlp


def _np_forward(params, x, activation="sigmoid"):
    def sigmoid(z):
        return 1.0 / (1.0 + np.exp(-z))

    acts = {"sigmoid": sigmoid, "relu": lambda z: np.maximum(z, 0)}
    a = acts[activation]
    h = x
    L = len([k for k in params if k.startswith("W")])
    for i in range(1, L + 1):
        h = h @ np.asarray(params[f"W{i}"]) + np.asarray(params[f"b{i}"])
        if i < L:
            h = a(h)
    return h


def test_init_shapes_reference_parity():
    """Reference shapes: W1 [784,100], W2 [100,10], b1 [100], b2 [10]
    (example.py:76-82)."""
    spec = mlp.MLPSpec()
    params = mlp.init(jax.random.PRNGKey(1), spec)
    assert params["W1"].shape == (784, 100)
    assert params["W2"].shape == (100, 10)
    assert params["b1"].shape == (100,)
    assert params["b2"].shape == (10,)
    assert all(np.asarray(v).dtype == np.float32 for v in params.values())
    # stddev-1 normal init (tf.random_normal default, example.py:76)
    assert 0.9 < np.asarray(params["W1"]).std() < 1.1
    assert np.asarray(params["b1"]).sum() == 0.0
    assert mlp.num_params(spec) == 784 * 100 + 100 + 100 * 10 + 10  # 79510


def test_init_deterministic():
    spec = mlp.MLPSpec()
    p1 = mlp.init(jax.random.PRNGKey(1), spec)
    p2 = mlp.init(jax.random.PRNGKey(1), spec)
    for k in p1:
        np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))


def test_forward_matches_numpy_oracle():
    spec = mlp.MLPSpec(input_size=12, hidden_sizes=(7,), num_classes=4)
    params = mlp.init(jax.random.PRNGKey(0), spec)
    x = np.random.RandomState(0).randn(5, 12).astype(np.float32)
    got = np.asarray(mlp.apply(spec, params, x))
    want = _np_forward(params, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_forward_deep_relu():
    spec = mlp.MLPSpec(input_size=6, hidden_sizes=(8, 5), num_classes=3,
                       activation="relu")
    params = mlp.init(jax.random.PRNGKey(2), spec)
    x = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    got = np.asarray(mlp.apply(spec, params, x))
    want = _np_forward(params, x, activation="relu")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
