"""TensorBoard event-writer tests: TFRecord framing + Event proto
round-trip; CRC32C native/python agreement on the known vector."""

import glob
import os

from distributed_tensorflow_example_tpu.native import _py_crc32c, crc32c, masked_crc32c
from distributed_tensorflow_example_tpu.utils.summary import SummaryWriter, read_event_file


def test_crc32c_known_vector():
    # RFC 3720 test vector: crc32c("123456789") == 0xE3069283
    assert crc32c(b"123456789") == 0xE3069283
    assert _py_crc32c(b"123456789") == 0xE3069283


def test_crc32c_native_matches_python():
    import os as _os
    import numpy as np

    rng = np.random.RandomState(0)
    for n in (0, 1, 7, 8, 9, 63, 1024):
        data = rng.bytes(n)
        assert crc32c(data) == _py_crc32c(data), n


def test_masked_crc_differs():
    assert masked_crc32c(b"abc") != crc32c(b"abc")


def test_event_file_roundtrip(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalars(1, {"cost": 2.5, "accuracy": 0.5})
    w.add_scalars(2, {"cost": 1.25, "accuracy": 0.75})
    w.close()
    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    assert len(files) == 1
    events = read_event_file(files[0])
    assert events[0]["file_version"] == "brain.Event:2"
    assert events[1]["step"] == 1
    assert abs(events[1]["scalars"]["cost"] - 2.5) < 1e-6
    assert abs(events[2]["scalars"]["accuracy"] - 0.75) < 1e-6
    assert events[1]["wall_time"] > 0
