"""TensorBoard event-writer tests: TFRecord framing + Event proto
round-trip; CRC32C native/python agreement on the known vector."""

import glob
import os

from distributed_tensorflow_example_tpu.native import _py_crc32c, crc32c, masked_crc32c
from distributed_tensorflow_example_tpu.utils.summary import SummaryWriter, read_event_file


def test_crc32c_known_vector():
    # RFC 3720 test vector: crc32c("123456789") == 0xE3069283
    assert crc32c(b"123456789") == 0xE3069283
    assert _py_crc32c(b"123456789") == 0xE3069283


def test_crc32c_native_matches_python():
    import os as _os
    import numpy as np

    rng = np.random.RandomState(0)
    for n in (0, 1, 7, 8, 9, 63, 1024):
        data = rng.bytes(n)
        assert crc32c(data) == _py_crc32c(data), n


def test_masked_crc_differs():
    assert masked_crc32c(b"abc") != crc32c(b"abc")


def test_event_file_roundtrip(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalars(1, {"cost": 2.5, "accuracy": 0.5})
    w.add_scalars(2, {"cost": 1.25, "accuracy": 0.75})
    w.close()
    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    assert len(files) == 1
    events = read_event_file(files[0])
    assert events[0]["file_version"] == "brain.Event:2"
    assert events[1]["step"] == 1
    assert abs(events[1]["scalars"]["cost"] - 2.5) < 1e-6
    assert abs(events[2]["scalars"]["accuracy"] - 0.75) < 1e-6
    assert events[1]["wall_time"] > 0


def test_graph_event_roundtrip(tmp_path):
    """The reference writes its graph into the event log
    (FileWriter(logs_path, graph=...), example.py:146); the writer's
    GraphDef record must parse back with the model's structure."""
    from distributed_tensorflow_example_tpu.utils.summary import mlp_graph_nodes

    w = SummaryWriter(str(tmp_path))
    w.add_graph(mlp_graph_nodes(784, (100,), 10, "sigmoid"))
    w.close()
    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    events = read_event_file(files[0])
    graphs = [e for e in events if e["graph_nodes"]]
    assert len(graphs) == 1
    nodes = {n["name"]: n for n in graphs[0]["graph_nodes"]}
    # the reference's graph shape: placeholders, variables, the two
    # matmuls, sigmoid, softmax, loss/metric/train ops
    for name in ("x", "y_", "W1", "b1", "W2", "b2", "global_step",
                 "y", "cross_entropy", "accuracy", "train"):
        assert name in nodes, name
    assert nodes["layer1/MatMul"]["op"] == "MatMul"
    assert nodes["layer1/MatMul"]["inputs"] == ["x", "W1"]
    assert nodes["a2"]["op"] == "Sigmoid"
    assert nodes["y"]["op"] == "Softmax"


def test_histogram_event_roundtrip(tmp_path):
    """HistogramProto encode/decode (Summary.Value field 5): bucket
    counts sum to the tensor size, min/max/sum/sum_squares survive,
    and scalar events in the same file still parse."""
    import numpy as np
    import pytest

    rng = np.random.RandomState(0)
    gvals = np.abs(rng.randn(37)) + 1e-3
    pvals = np.abs(rng.randn(5)) + 1e-3
    w = SummaryWriter(str(tmp_path))
    w.add_scalars(1, {"cost": 2.5})
    w.add_histograms(2, {"grad_norm": gvals, "param_norm": pvals})
    w.close()
    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    events = read_event_file(files[0])
    assert events[1]["scalars"]["cost"] == pytest.approx(2.5)
    assert not events[1]["histograms"]
    he = events[2]
    assert he["step"] == 2
    assert set(he["histograms"]) == {"grad_norm", "param_norm"}
    for tag, vals in (("grad_norm", gvals), ("param_norm", pvals)):
        h = he["histograms"][tag]
        assert sum(h["bucket"]) == pytest.approx(vals.size)
        assert h["num"] == pytest.approx(vals.size)
        assert h["min"] == pytest.approx(vals.min())
        assert h["max"] == pytest.approx(vals.max())
        assert h["sum"] == pytest.approx(vals.sum())
        assert h["sum_squares"] == pytest.approx(np.square(vals).sum())
        assert len(h["bucket"]) == len(h["bucket_limit"])
        # right edges are sorted and end at max
        assert h["bucket_limit"] == sorted(h["bucket_limit"])
        assert h["bucket_limit"][-1] == pytest.approx(vals.max())


def test_histogram_degenerate_and_empty():
    """All-equal values collapse to one bucket; empty input is a
    caller error, not a silent zero-histogram."""
    import numpy as np
    import pytest

    from distributed_tensorflow_example_tpu.utils.summary import (
        _parse_histogram, encode_histogram_proto)

    h = _parse_histogram(encode_histogram_proto(np.full(8, 3.25)))
    assert h["bucket"] == [8.0]
    assert h["bucket_limit"] == [3.25]
    assert h["min"] == h["max"] == 3.25
    with pytest.raises(ValueError, match="empty"):
        encode_histogram_proto(np.array([]))


def test_histogram_nonfinite_values_survive():
    """A diverging run's inf/NaN norms must be RECORDED, not crash the
    writer at the window boundary (the histogram exists to show the
    divergence): non-finite values clamp into the finite range's edge
    buckets, counts still sum to the tensor size; an all-non-finite
    tensor collapses to one bucket."""
    import numpy as np
    import pytest

    from distributed_tensorflow_example_tpu.utils.summary import (
        _parse_histogram, encode_histogram_proto)

    vals = np.array([1.0, 2.0, np.inf, -np.inf, np.nan, 3.0])
    h = _parse_histogram(encode_histogram_proto(vals))
    assert h["num"] == vals.size
    assert sum(h["bucket"]) == pytest.approx(vals.size)
    assert h["min"] == 1.0 and h["max"] == 3.0  # the finite range
    assert np.isfinite(h["sum"]) and np.isfinite(h["sum_squares"])
    h2 = _parse_histogram(encode_histogram_proto(
        np.array([np.inf, np.nan])))
    assert h2["bucket"] == [2.0]
    assert h2["min"] == h2["max"] == 0.0


def test_run_writes_graph_event(tmp_path):
    """End-to-end: a training run's event file carries the graph record
    (example.py:146 parity), alongside the per-step scalars."""
    from distributed_tensorflow_example_tpu.config import Config
    from distributed_tensorflow_example_tpu.train.loop import run

    run(Config(
        training_epochs=1, batch_size=32, dataset="synthetic",
        synthetic_train_size=64, synthetic_test_size=32,
        logs_path=str(tmp_path), frequency=2, compilation_cache="",
    ))
    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    assert len(files) == 1
    events = read_event_file(files[0])
    assert any(e["graph_nodes"] for e in events)
    assert any(e["scalars"].get("cost") is not None for e in events)
