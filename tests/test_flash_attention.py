"""Flash-attention kernel tests (interpret mode on CPU): numerical
parity with dense attention, causal frontier skipping, padding, and
gradients via the custom VJP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.ops import flash_attention as fa
from distributed_tensorflow_example_tpu.ops import ring_attention as ra


def _inputs(b=2, s=512, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(b, s, h, d).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_matches_dense(causal):
    q, k, v = _inputs()
    want = np.asarray(ra.attention(q, k, v, causal=causal))
    got = np.asarray(fa.flash_attention(q, k, v, causal))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_multiple_tiles_causal():
    """Sequence spanning several tiles; future k tiles must reduce to
    arithmetic no-ops under the global-position mask."""
    q, k, v = _inputs(s=1024, seed=2)
    want = np.asarray(ra.attention(q, k, v, causal=True))
    got = np.asarray(fa.flash_attention(q, k, v, True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ragged_causal_padding():
    """S not a multiple of the tile: padded key rows sit strictly in
    the causal future of every real q row, so results are exact."""
    q, k, v = _inputs(s=300, seed=3)
    want = np.asarray(ra.attention(q, k, v, causal=True))
    got = np.asarray(fa.flash_attention(q, k, v, True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ragged_full_falls_back_exactly():
    """Non-causal ragged shapes route to the dense path (documented);
    results must still be exact."""
    q, k, v = _inputs(s=300, seed=4)
    want = np.asarray(ra.attention(q, k, v, causal=False))
    got = np.asarray(fa.flash_attention(q, k, v, False))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_grads_match_dense_multitile_causal():
    """Kernel backward across several q/k tiles under the causal mask."""
    q, k, v = _inputs(s=1024, seed=8)

    def loss(fn, q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_, True) ** 2)

    g_flash = jax.grad(
        lambda q_, k_, v_: loss(fa.flash_attention, q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_dense = jax.grad(
        lambda q_, k_, v_: loss(
            lambda a, b_, c, caus: ra.attention(a, b_, c, causal=caus),
            q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=5e-4, atol=5e-5,
            err_msg=name,
        )


@pytest.mark.parametrize("s", [200, 300])
def test_grads_ragged_causal_kernel_path(s, monkeypatch):
    """Causal S not a multiple of the tile: the VJP pads to the tile
    multiple and stays on the O(S·blk) kernels — no dense recompute
    (VERDICT r2 weak #6). The dense fallback is poisoned to prove the
    kernel path is the one that runs."""
    q, k, v = _inputs(s=s, seed=9)

    def loss(fn, q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_, True) ** 2)

    g_dense = jax.grad(
        lambda q_, k_, v_: loss(
            lambda a, b_, c, caus: ra.attention(a, b_, c, causal=caus),
            q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)

    def _poisoned(*a, **kw):
        raise AssertionError("dense fallback must not run for causal ragged")

    monkeypatch.setattr(fa, "dense_attention", _poisoned)
    g_flash = jax.grad(
        lambda q_, k_, v_: loss(fa.flash_attention, q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=5e-4, atol=5e-5,
            err_msg=name,
        )


def test_grads_ragged_full_dense_fallback():
    """Non-causal ragged S: padded keys would corrupt real rows, so
    BOTH directions stay on the exact dense path."""
    q, k, v = _inputs(s=200, seed=9)

    def loss(fn, q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_, False) ** 2)

    g_flash = jax.grad(
        lambda q_, k_, v_: loss(fa.flash_attention, q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_dense = jax.grad(
        lambda q_, k_, v_: loss(
            lambda a, b_, c, caus: ra.attention(a, b_, c, causal=caus),
            q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=5e-4, atol=5e-5,
            err_msg=name,
        )


def test_grads_match_dense():
    q, k, v = _inputs(s=512, seed=5)

    def loss(fn, q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_, True) ** 2)

    g_flash = jax.grad(
        lambda q_, k_, v_: loss(fa.flash_attention, q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_dense = jax.grad(
        lambda q_, k_, v_: loss(
            lambda a, b_, c, caus: ra.attention(a, b_, c, causal=caus),
            q_, k_, v_),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=5e-4, atol=5e-5,
            err_msg=name,
        )


def test_cross_length_falls_back_to_dense():
    """k shorter than q (non-causal): the kernel cannot tile the
    rectangular score geometry, so the dense path must be taken — and
    be exact (ADVICE r2: this used to die in prep() with a reshape
    error)."""
    rng = np.random.RandomState(11)
    q = rng.randn(2, 512, 2, 8).astype(np.float32)
    k = rng.randn(2, 256, 2, 8).astype(np.float32)
    v = rng.randn(2, 256, 2, 8).astype(np.float32)
    want = np.asarray(ra.attention(q, k, v, causal=False))
    got = np.asarray(fa.flash_attention(q, k, v, False))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_cross_length_causal_rejected():
    """Causal cross-length has no conventional alignment here; it must
    raise a clear ValueError, not a reshape failure (ADVICE r2)."""
    rng = np.random.RandomState(12)
    q = rng.randn(2, 512, 2, 8).astype(np.float32)
    k = rng.randn(2, 256, 2, 8).astype(np.float32)
    v = rng.randn(2, 256, 2, 8).astype(np.float32)
    with pytest.raises(ValueError, match="equal q/k lengths"):
        fa.flash_attention(q, k, v, True)
    with pytest.raises(ValueError, match="equal q/k lengths"):
        ra.attention(q, k, v, causal=True)


def test_rectangular_tiles_causal_s2048():
    """S=2048 picks the r5 rectangular geometry (blk_q=2048,
    blk_k=1024) — the generalized causal tile classes and the
    frontier-clamped fetch indices (_causal_frontier/_causal_first_q)
    must stay exact for blk_q != blk_k in the forward AND all three
    backward kernels (no smaller test reaches this path: square
    tiles are picked for every S < 2048)."""
    bq, bk = fa._pick_tiles(2048, 8)
    assert (bq, bk) == (2048, 1024), "geometry drifted; update test"
    q, k, v = _inputs(b=1, s=2048, h=1, d=8)

    want = np.asarray(ra.attention(q, k, v, causal=True))
    got = np.asarray(fa.flash_attention(q, k, v, True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def loss_fa(q_, k_, v_):
        return jnp.sum(fa.flash_attention(q_, k_, v_, True) ** 2)

    def loss_ra(q_, k_, v_):
        return jnp.sum(ra.attention(q_, k_, v_, causal=True) ** 2)

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ra = jax.grad(loss_ra, argnums=(0, 1, 2))(q, k, v)
    for got_g, want_g, name in zip(g_fa, g_ra, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got_g), np.asarray(want_g), rtol=5e-4,
            atol=5e-4, err_msg=f"d{name}")


def test_pick_tiles_wide_head_vmem_cap():
    """ADVICE r5 #1: for D > 128 the doubled blk_q is bounded by the
    same 512 VMEM cap as blk_k (square tiles) — the backward kernels'
    [blk_q, blk_k] intermediates and q/do fetch buffers already scale
    with D/128, and doubling q on top would run twice the scoped-VMEM
    budget. D <= 128 keeps the 2:1 rectangular geometry."""
    assert fa._pick_tiles(4096, 64) == (2048, 1024)
    assert fa._pick_tiles(4096, 128) == (2048, 1024)
    # wide heads: blk_q capped with blk_k at 512
    assert fa._pick_tiles(4096, 256) == (512, 512)
    assert fa._pick_tiles(2048, 256) == (512, 512)
    assert fa._pick_tiles(1024, 256) == (512, 512)
    # s too short to double: unchanged either way
    assert fa._pick_tiles(512, 256) == (512, 512)
    assert fa._pick_tiles(256, 256) == (256, 256)


@pytest.mark.skipif(
    not hasattr(fa.pltpu, "CompilerParams"),
    reason="pallas CompilerParams API needs a newer jax than this env")
def test_d256_capped_tiles_match_dense():
    """Functional check at d_head=256 (the capped square-tile path):
    forward and all three gradients match dense attention."""
    q, k, v = _inputs(b=1, s=512, h=1, d=256)
    want = np.asarray(ra.attention(q, k, v, causal=True))
    got = np.asarray(fa.flash_attention(q, k, v, True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def loss_fa(q_, k_, v_):
        return jnp.sum(fa.flash_attention(q_, k_, v_, True) ** 2)

    def loss_ra(q_, k_, v_):
        return jnp.sum(ra.attention(q_, k_, v_, causal=True) ** 2)

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ra = jax.grad(loss_ra, argnums=(0, 1, 2))(q, k, v)
    for got_g, want_g, name in zip(g_fa, g_ra, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got_g), np.asarray(want_g), rtol=2e-3,
            atol=2e-3, err_msg=f"d{name}")
